"""Round-13 in-collective quantization: in-band scales, stochastic rounding,
error feedback, the quantized hot-row reduce, and the compiled-HLO byte pins.

Covers the round-13 tentpole contracts:
- `pack_inband`/`unpack_inband` round-trip multi-block payloads (dim > 32,
  including a partial trailing block) within format tolerance, and the wire
  arrays carry the CARRIER dtype (bf16 ships as uint16 so XLA:CPU's bf16->f32
  float normalization can't silently widen the compiled collectives);
- stochastic rounding stays within one quantization step, is unbiased across
  elements, and is deterministic (the dither is a key-free hash: the same
  payload re-encodes identically, which resume/replay parity depends on);
- per-row error feedback: the time-averaged served value converges to the
  true row where plain int8 quantization leaves a persistent bias;
- `EmbeddingTableState.ef` gating (`MeshTrainer.ef_for`) and persistence:
  residuals survive `save_sharded`/`load_sharded` AND the incremental delta
  feed bit-exactly (streamed under the reserved "__ef__" slot name);
- the quantized hot-row backward (`hot_wire=`): parity within format
  tolerance vs the fp32 psum plan, with the replicated cache staying
  bit-identical across devices (a diverged replica is silent corruption);
- the compiled-HLO byte pins: fp32 wire compiles byte-identical to the
  round-12 exchange (34048 a2a bytes, 3 a2as, no narrow dtypes), and the
  checked-in hlo-budget records int8 <= bf16 <= fp32 with the int8 in-band
  config >= 40% under the fp32 baseline — all with wire_model_delta 0 (the
  analytic cost model prices exactly what the compiled program ships).

The suite-wide default wire is pinned to fp32 in tests/conftest.py; every
lossy-format test here passes `wire=`/`hot_wire=` explicitly.
"""

import json
import os

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.model import EmbeddingModel
from openembedding_tpu.ops import wire
from openembedding_tpu.parallel import (MeshTrainer, load_sharded, make_mesh,
                                        save_sharded)

S = 8  # conftest forces 8 virtual CPU devices
B = 8 * S
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# in-band codec units
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt", ["fp32", "bf16", "int8"])
def test_pack_inband_multiblock_roundtrip(fmt):
    """dim 80 = two full 32-blocks + a partial 16-block: per-BLOCK scales
    must quantize each block against its own max, and the partial block's
    padding must not leak into decoded values."""
    dim = 80
    rng = np.random.default_rng(0)
    rows = rng.standard_normal((64, dim)).astype(np.float32)
    rows[:, 32:64] *= 100.0   # wildly different block magnitudes
    rows[:, 64:] *= 0.01
    rows[5] = 0.0             # all-zero row: zero scales, exact zeros back
    wired = wire.pack_inband(jnp.asarray(rows), fmt)
    assert wired.shape == (64, wire.rows_wire_width(dim, fmt))
    assert wired.dtype == wire.wire_carrier_dtype(fmt)
    dec = np.asarray(wire.unpack_inband(wired, dim, fmt))
    if fmt == "fp32":
        np.testing.assert_array_equal(dec, rows)
    elif fmt == "bf16":
        np.testing.assert_allclose(dec, rows, rtol=2 ** -8, atol=1e-7)
    else:
        # per-BLOCK max-abs scaling: error <= half a step of the OWN block's
        # scale — the 100x block must not poison the 0.01x block's precision
        for lo in range(0, dim, wire.INBAND_BLOCK):
            hi = min(lo + wire.INBAND_BLOCK, dim)
            step = np.abs(rows[:, lo:hi]).max(axis=1, keepdims=True) / 127.0
            assert np.all(np.abs(dec[:, lo:hi] - rows[:, lo:hi])
                          <= step * 0.5 + 1e-7), (lo, hi)
    np.testing.assert_array_equal(dec[5], 0.0)


def test_stochastic_rounding_bounds_unbiased_deterministic():
    """SR moves each element at most ONE quantization step, is unbiased
    across a large payload (mean error ~ 0), and is deterministic — the
    dither is a key-free hash of value + position, so re-encoding the same
    payload gives the same bits (replay/resume parity)."""
    dim = 64
    rng = np.random.default_rng(1)
    rows = rng.standard_normal((512, dim)).astype(np.float32)
    w1 = wire.pack_inband(jnp.asarray(rows), "int8", stochastic=True)
    w2 = wire.pack_inband(jnp.asarray(rows), "int8", stochastic=True)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    dec = np.asarray(wire.unpack_inband(w1, dim, "int8"))
    err = dec - rows
    for lo in range(0, dim, wire.INBAND_BLOCK):
        hi = lo + wire.INBAND_BLOCK
        step = np.abs(rows[:, lo:hi]).max(axis=1, keepdims=True) / 127.0
        assert np.all(np.abs(err[:, lo:hi]) <= step + 1e-7)
    # unbiasedness: the mean error over 32k elements is far below the mean
    # HALF-step a deterministic round-to-nearest would be allowed to sit at
    mean_step = float(np.abs(rows).max(axis=1).mean() / 127.0)
    assert abs(float(err.mean())) < 0.05 * mean_step


def test_error_feedback_time_average_converges():
    """The owner-edge EF loop (serve q(w+ef), ef <- (w+ef) - deq(q)): for a
    CONSTANT row the time-averaged served value must converge onto the true
    value, while plain int8 quantization keeps its full one-shot bias."""
    dim = 16
    rng = np.random.default_rng(2)
    w = rng.standard_normal((32, dim)).astype(np.float32)
    ef = np.zeros_like(w)
    served = []
    for _ in range(32):
        wired = wire.pack_inband(jnp.asarray(w + ef), "int8")
        deq = np.asarray(wire.unpack_inband(wired, dim, "int8"))
        ef = (w + ef) - deq
        served.append(deq)
    avg_err = np.abs(np.mean(served, axis=0) - w).max()
    one_shot_err = np.abs(served[0] - w).max()
    assert one_shot_err > 0  # quantization actually bites at these scales
    assert avg_err < 0.2 * one_shot_err, (avg_err, one_shot_err)


# ---------------------------------------------------------------------------
# trainer EF state: gating + persistence
# ---------------------------------------------------------------------------


class _Tower(nn.Module):
    @nn.compact
    def __call__(self, embedded, dense):
        bias = self.param("bias", nn.initializers.zeros, (1,), jnp.float32)
        return (jnp.sum(embedded["a"].astype(jnp.float32), axis=(1, 2))
                + jnp.sum(embedded["b"].astype(jnp.float32), axis=(1, 2))
                + bias[0])


def _model(vocab=256):
    return EmbeddingModel(_Tower(), [
        embed.Embedding(vocab, 8, name="a"),
        embed.Embedding(-1, 8, name="b", capacity=4096),
    ])


def _batch(rng, vocab=256):
    a = rng.integers(0, vocab, (B, 4)).astype(np.int32)
    b = rng.integers(0, 1 << 40, (B, 3)).astype(np.int64)
    a[:, 0] = 7  # duplicates: count lanes carry > 1
    return {"sparse": {"a": a, "b": b},
            "label": rng.integers(0, 2, (B,)).astype(np.float32)}


def _ef_by_key(ts):
    """(ids, ef rows, weight rows) in key order for a hash table — restore
    re-admits keys, so physical slot order is not comparable across states."""
    from openembedding_tpu.ops.id64 import np_resident_ids
    mask, ids = np_resident_ids(np.asarray(ts.keys))
    order = np.argsort(ids)
    return (ids[order], np.asarray(ts.ef)[mask][order],
            np.asarray(ts.weights)[mask][order])


def _assert_ef_equal(live, restored):
    for name, ts in live.tables.items():
        got = restored.tables[name]
        assert got.ef is not None, name
        assert "__ef__" not in got.slots  # hoisted back out of the slot dict
        if ts.keys is None:  # array table: slot order is the id order
            np.testing.assert_array_equal(np.asarray(ts.ef),
                                          np.asarray(got.ef), err_msg=name)
            np.testing.assert_array_equal(np.asarray(ts.weights),
                                          np.asarray(got.weights),
                                          err_msg=name)
        else:
            ids0, ef0, w0 = _ef_by_key(ts)
            ids1, ef1, w1 = _ef_by_key(got)
            np.testing.assert_array_equal(ids0, ids1, err_msg=name)
            np.testing.assert_array_equal(ef0, ef1, err_msg=name)
            np.testing.assert_array_equal(w0, w1, err_msg=name)


def _train_steps(tr, batches):
    state = tr.init(batches[0])
    step = tr.jit_train_step(batches[0], state)
    for b in batches:
        state, m = step(state, b)
        assert np.isfinite(float(m["loss"]))
    return state


def test_ef_state_gating():
    """`ef_for`: residuals attach exactly when the lossy pull needs them —
    on for int8 wire, off for fp32/bf16 unless `error_feedback=True` forces
    them; the arrays shard like the weights they correct."""
    rng = np.random.default_rng(3)
    b = _batch(rng)
    for wire_fmt, ef_flag, expect in (("int8", None, True),
                                      ("fp32", None, False),
                                      ("bf16", None, False),
                                      ("bf16", True, True),
                                      ("int8", False, False)):
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire=wire_fmt,
                         error_feedback=ef_flag)
        state = tr.init(b)
        for name, ts in state.tables.items():
            if expect:
                assert ts.ef is not None, (wire_fmt, ef_flag, name)
                assert ts.ef.shape == ts.weights.shape
                assert ts.ef.dtype == jnp.float32
            else:
                assert ts.ef is None, (wire_fmt, ef_flag, name)


def test_ef_survives_sharded_checkpoint(tmp_path):
    """Trained residuals round-trip `save_sharded`/`load_sharded` bit-exactly
    (streamed under the reserved "__ef__" slot name; a fresh trainer's zero
    template is fully replaced)."""
    rng = np.random.default_rng(4)
    batches = [_batch(rng) for _ in range(3)]
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="int8")
    state = _train_steps(tr, batches)
    assert any(float(jnp.abs(ts.ef).max()) > 0
               for ts in state.tables.values())  # residuals actually moved
    save_sharded(state, tr.model, str(tmp_path), num_shards=S,
                 include_optimizer=True)

    tr2 = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                      mesh=make_mesh(), wire="int8")
    restored = load_sharded(tr2.init(batches[0]), tr2.model, str(tmp_path),
                            num_shards=S)
    _assert_ef_equal(state, restored)


def test_ef_survives_incremental_persister(tmp_path):
    """base + delta replay restores the residuals bit for bit — the
    IncrementalPersister's touched-row reader streams ef under "__ef__"
    beside the optimizer slots."""
    from openembedding_tpu.persist import (IncrementalPersister,
                                           PersistPolicy, list_deltas,
                                           restore_server_model)
    rng = np.random.default_rng(5)
    batches = [_batch(rng) for _ in range(4)]
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="int8")
    state = tr.init(batches[0])
    step = tr.jit_train_step(batches[0], state)
    root = str(tmp_path / "persist")
    with IncrementalPersister(tr, tr.model, root, window=2, keep=10,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        for b in batches:
            state, _m = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()
    assert list_deltas(root)  # the chain actually has deltas to replay

    tr2 = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                      mesh=make_mesh(), wire="int8")
    restored = restore_server_model(tr2.init(batches[0]), tr2.model, root,
                                    trainer=tr2)
    _assert_ef_equal(state, restored)


# ---------------------------------------------------------------------------
# quantized hot-row reduce
# ---------------------------------------------------------------------------

_HOT_IDS = {"a": np.array([7, 13], np.int64)}


@pytest.mark.parametrize("hot_fmt,tol", [("bf16", 0.02), ("int8", 0.06)])
def test_hot_reduce_parity_and_replica_identity(hot_fmt, tol):
    """`hot_wire=` quantizes ONLY the dense (H, dim) gradient reduction: the
    trained tables stay within format tolerance of the fp32 psum plan, and —
    the corruption pin — every device's replica of the hot cache is
    BIT-identical after training (the two-stage int8 reduce must hand every
    replica the same re-encoded bytes; a diverged cache poisons all
    subsequent pulls differently per shard)."""
    rng = np.random.default_rng(6)
    batches = [_batch(rng) for _ in range(3)]

    def run(hot_wire):
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire="fp32", hot_rows=64,
                         hot_wire=hot_wire)
        state = tr.init(batches[0])
        state = tr.refresh_hot_rows(state, hot_ids=_HOT_IDS)
        step = tr.jit_train_step(batches[0], state)
        for b in batches:
            state, m = step(state, b)
            assert np.isfinite(float(m["loss"]))
        assert int(np.asarray(m["stats"]["a/hot_hits"])) > 0
        return tr, state

    _tr0, s_ref = run(None)           # fp32 psum plan
    tr1, s_q = run(hot_fmt)
    hot = s_q.tables["a"].hot
    shards = [np.asarray(sh.data) for sh in hot.weights.addressable_shards]
    for sh in shards[1:]:
        np.testing.assert_array_equal(shards[0], sh)
    ref, got = s_ref.tables["a"].hot.weights, hot.weights
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=tol, atol=tol)
    # the shard arrays (cold tail) never went through the hot reduce
    s_ref_synced = _tr0.hot_sync(s_ref)
    s_q_synced = tr1.hot_sync(s_q)
    np.testing.assert_allclose(np.asarray(s_q_synced.tables["a"].weights),
                               np.asarray(s_ref_synced.tables["a"].weights),
                               rtol=tol, atol=tol)


# ---------------------------------------------------------------------------
# compiled-HLO byte pins
# ---------------------------------------------------------------------------


def test_fp32_wire_compiles_byte_identical_to_round12():
    """OETPU_WIRE=fp32 is the opt-out: the compiled exchange must be the
    round-12 program byte for byte — 3 a2as, 34048 payload bytes, no narrow
    carrier dtypes anywhere near a collective, model delta 0."""
    from tools.oelint.passes.hlo_budget import (CONFIGS, make_trainer,
                                                measure_trainer)
    (config,) = [c for c in CONFIGS if c["name"] == "fused_fp32"]
    trainer, batch = make_trainer(config)
    got = measure_trainer(trainer, batch)
    assert got["all_to_all"] == 3
    assert got["hlo_a2a_bytes"] == 34048   # the round-12 pinned budget
    assert got["wire_model_delta"] == 0
    for narrow in ("s8", "u8", "u16", "bf16", "f16"):
        assert narrow not in got["hlo_a2a_dtypes"].split(","), got


def test_budget_orderings_and_int8_cut():
    """The checked-in hlo-budget (regenerated by `--update-budget`, enforced
    by `make lint`) must keep the round-13 acceptance numbers: compiled a2a
    bytes int8 <= bf16 <= fp32, the int8 in-band config >= 40% under the
    fp32 hot baseline, and every config's analytic model exact (delta 0;
    pipelined configs may differ by exactly the recorded overlapped-prefetch
    bytes, which the serial analytic model deliberately excludes)."""
    with open(os.path.join(REPO, "tools", "oelint",
                           "hlo_budget.json")) as f:
        cfg = json.load(f)["configs"]
    int8 = cfg["fused_int8_inband"]["hlo_a2a_bytes"]
    bf16 = cfg["fused_bf16_inband"]["hlo_a2a_bytes"]
    fp32 = cfg["fused_fp32_hot"]["hlo_a2a_bytes"]
    assert int8 <= bf16 <= fp32, (int8, bf16, fp32)
    assert int8 <= 0.6 * fp32, (int8, fp32)  # >= 40% fewer exchange bytes
    assert cfg["fused_fp32"]["hlo_a2a_bytes"] == fp32  # hot cache rides free
    for name, c in cfg.items():
        allowed = (0, c.get("wire_overlapped_bytes", 0))
        assert c["wire_model_delta"] in allowed, (name, c["wire_model_delta"])
