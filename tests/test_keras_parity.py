"""Optimizer parity against REAL Keras apply_gradients.

Direct counterpart of the reference's `test/optimizer_test.py`: each optimizer
config runs the same gradient sequence through Keras (TF backend, CPU) and through
our fused sparse apply with every row touched each step (so per-row beta^t equals
Keras's global iteration), then weights must match. The reference accepts summed
abs error < 10.0; we assert per-element 1e-4."""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

import jax.numpy as jnp  # noqa: E402

from openembedding_tpu import optimizers as opts  # noqa: E402
from openembedding_tpu.ops.sparse import sparse_apply_dense_table  # noqa: E402

ROWS, DIM, STEPS = 8, 6, 5

CONFIGS = [
    keras.optimizers.SGD(learning_rate=0.1),
    keras.optimizers.SGD(learning_rate=0.1, momentum=0.9),
    keras.optimizers.SGD(learning_rate=0.1, momentum=0.9, nesterov=True),
    keras.optimizers.Adagrad(learning_rate=0.1),
    keras.optimizers.Adagrad(learning_rate=0.1, initial_accumulator_value=0.5),
    keras.optimizers.Adadelta(learning_rate=0.5),
    keras.optimizers.Adadelta(learning_rate=0.5, rho=0.8),
    keras.optimizers.Adam(learning_rate=0.01),
    keras.optimizers.Adam(learning_rate=0.01, beta_1=0.5, beta_2=0.9),
    keras.optimizers.Adamax(learning_rate=0.01),
    keras.optimizers.RMSprop(learning_rate=0.01),
    keras.optimizers.RMSprop(learning_rate=0.01, rho=0.8, momentum=0.5),
    keras.optimizers.Ftrl(learning_rate=0.1),
    keras.optimizers.Ftrl(learning_rate=0.1, l1_regularization_strength=0.01,
                          l2_regularization_strength=0.01),
    keras.optimizers.Ftrl(learning_rate=0.1, learning_rate_power=-0.7),
    keras.optimizers.Ftrl(learning_rate=0.1, beta=0.5),
    keras.optimizers.Ftrl(learning_rate=0.1,
                          l2_shrinkage_regularization_strength=0.01),
]


def _name(k):
    cfg = k.get_config()
    parts = [type(k).__name__] + [
        f"{key}={cfg[key]}" for key in sorted(cfg)
        if key in ("momentum", "nesterov", "rho", "beta_1", "beta_2", "beta",
                   "initial_accumulator_value", "l1_regularization_strength",
                   "l2_regularization_strength", "learning_rate_power",
                   "l2_shrinkage_regularization_strength") and cfg[key]]
    return ",".join(parts)


@pytest.mark.parametrize("keras_opt", CONFIGS, ids=_name)
def test_matches_keras_apply_gradients(keras_opt):
    rng = np.random.default_rng(42)
    w0 = rng.normal(size=(ROWS, DIM)).astype(np.float32)
    grads = [rng.normal(size=(ROWS, DIM)).astype(np.float32)
             for _ in range(STEPS)]

    var = keras.Variable(w0.copy())
    kopt = type(keras_opt).from_config(keras_opt.get_config())
    for g in grads:
        kopt.apply_gradients([(keras.ops.convert_to_tensor(g), var)])
    want = np.asarray(var)

    sparse_opt = opts.from_keras(keras_opt)
    w = jnp.asarray(w0)
    slots = sparse_opt.init_slots(ROWS, DIM)
    ids = jnp.arange(ROWS)   # touch every row every step
    for g in grads:
        w, slots = sparse_apply_dense_table(sparse_opt, w, slots, ids,
                                            jnp.asarray(g))
    np.testing.assert_allclose(np.asarray(w), want, rtol=1e-4, atol=1e-4)


def test_rejected_configs():
    with pytest.raises(ValueError):
        opts.from_keras(keras.optimizers.Adam(amsgrad=True))
    with pytest.raises(ValueError):
        opts.from_keras(keras.optimizers.RMSprop(centered=True))
    with pytest.raises(ValueError):
        opts.from_keras(keras.optimizers.SGD(weight_decay=0.1))
