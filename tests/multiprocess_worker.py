"""Worker process for tests/test_multiprocess.py — a REAL multi-process
cluster member (the reference's test strategy forks real server processes,
`entry/c_api_test.h:195,285`; here each process runs `jax.distributed` with 2
local CPU devices and the mesh spans all processes).

Invoked as:  python multiprocess_worker.py <scenario> <pid> <nprocs> <port> <tmp>
Scenarios:
  train_ckpt  — multihost.global_batch + MeshTrainer steps (losses recorded for
                the single-process oracle) + save_sharded/load_sharded across
                processes with shard-exact restore.
  persist_ok  — AsyncPersister multi-host commit: every process writes its
                shards + done marker, process 0 commits; restore verified.
  persist_kill— process N-1 dies before persisting (crash mid-checkpoint):
                process 0 must time out waiting for the done marker and NO
                COMMIT may appear (crash consistency).
"""

import json
import os
import sys


def log(pid, msg):
    print(f"[worker {pid}] {msg}", file=sys.stderr, flush=True)


def make_global_batch(step, gb):
    import numpy as np
    rng = np.random.default_rng(100 + step)
    ids = rng.integers(0, 1024, size=(gb, 3)).astype(np.int64)
    dense = rng.standard_normal((gb, 4)).astype(np.float32)
    label = (rng.random(gb) < 0.5).astype(np.float32)
    return {"sparse": {"categorical": ids}, "dense": dense, "label": label}


def local_slice(full, pid, n):
    import jax.tree_util as jtu
    gb = full["label"].shape[0]
    lo, hi = pid * gb // n, (pid + 1) * gb // n
    return jtu.tree_map(lambda x: x[lo:hi], full)


def build_trainer(mesh):
    import openembedding_tpu as embed
    from openembedding_tpu.models import make_wdl
    from openembedding_tpu.parallel import MeshTrainer

    model = make_wdl(vocabulary=1024, dim=4, hidden=(16,))
    return MeshTrainer(model, embed.Adagrad(learning_rate=0.1), mesh=mesh,
                       seed=0)


def scenario_train_ckpt(pid, n, tmp):
    import numpy as np
    import jax
    from jax.experimental import multihost_utils
    from openembedding_tpu.parallel import make_mesh, multihost

    mesh = make_mesh()
    trainer = build_trainer(mesh)
    gb = 32
    batches = [multihost.global_batch(
        local_slice(make_global_batch(s, gb), pid, n), mesh)
        for s in range(4)]
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    losses = []
    for b in batches:
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    log(pid, f"losses {losses}")

    ck = os.path.join(tmp, "ckpt")
    # keep host copies of this process's shards for the post-load comparison
    before = {s.device: np.asarray(s.data)
              for s in state.tables["categorical"].weights.addressable_shards}
    trainer.save(state, ck)
    multihost_utils.sync_global_devices("ckpt_written")

    trainer2 = build_trainer(mesh)
    state2 = trainer2.init(batches[0])
    state2 = trainer2.load(state2, ck)
    for s in state2.tables["categorical"].weights.addressable_shards:
        np.testing.assert_allclose(np.asarray(s.data), before[s.device],
                                   rtol=0, atol=0)
    assert int(state2.step) == 4
    multihost_utils.sync_global_devices("ckpt_verified")

    if pid == 0:
        with open(os.path.join(tmp, "result.json"), "w") as f:
            json.dump({"ok": True, "losses": losses,
                       "num_processes": n,
                       "num_devices": len(jax.devices())}, f)


def scenario_persist_ok(pid, n, tmp):
    import openembedding_tpu as embed
    from jax.experimental import multihost_utils
    from openembedding_tpu.parallel import make_mesh, multihost
    from openembedding_tpu.persist import latest_persist, restore_server_model

    mesh = make_mesh()
    trainer = build_trainer(mesh)
    gb = 16
    b = multihost.global_batch(
        local_slice(make_global_batch(0, gb), pid, n), mesh)
    state = trainer.init(b)
    step = trainer.jit_train_step(b, state)
    state, _ = step(state, b)

    root = os.path.join(tmp, "persists")
    with embed.AsyncPersister(trainer, trainer.model, root,
                              policy=embed.PersistPolicy(every_steps=1),
                              commit_timeout=300.0) as p:
        p.persist(state)
        p.wait()
    multihost_utils.sync_global_devices("persist_done")

    path = latest_persist(root)
    assert path is not None, "no committed persist"
    # restore is a COLLECTIVE (init + load compile global-mesh programs):
    # every process participates, exactly like a real pod relaunch
    trainer2 = build_trainer(mesh)
    state2 = trainer2.init(b)
    state2 = restore_server_model(state2, trainer2.model, root,
                                  trainer=trainer2)
    assert int(state2.step) == 1
    multihost_utils.sync_global_devices("persist_verified")
    if pid == 0:
        with open(os.path.join(tmp, "result.json"), "w") as f:
            json.dump({"ok": True, "committed": path}, f)


def scenario_persist_kill(pid, n, tmp):
    import openembedding_tpu as embed
    from openembedding_tpu.parallel import make_mesh, multihost
    from openembedding_tpu.persist import list_persists

    mesh = make_mesh()
    trainer = build_trainer(mesh)
    gb = 16
    b = multihost.global_batch(
        local_slice(make_global_batch(0, gb), pid, n), mesh)
    state = trainer.init(b)
    step = trainer.jit_train_step(b, state)
    state, _ = step(state, b)

    root = os.path.join(tmp, "persists")
    if pid == n - 1:
        # Simulate a process wedging mid-checkpoint: its shards and done
        # marker never appear. (A hard os._exit would ALSO make the jax
        # coordination service kill the healthy processes before they can
        # observe the timeout — a different failure domain than the commit
        # protocol under test.) Wait for process 0's verdict, then exit.
        log(pid, "simulating wedged writer (no shards, no done marker)")
        import time
        deadline = time.monotonic() + 120
        while (not os.path.exists(os.path.join(tmp, "result.json"))
               and time.monotonic() < deadline):
            time.sleep(0.2)
        return

    err = None
    try:
        with embed.AsyncPersister(trainer, trainer.model, root,
                                  policy=embed.PersistPolicy(every_steps=1),
                                  commit_timeout=5.0) as p:
            p.persist(state)
            p.wait()
    except RuntimeError as e:
        err = str(e)
    if pid == 0:
        assert err is not None and "finished writing" in err, \
            f"commit wait should have timed out, got {err!r}"
        assert list_persists(root) == [], "a COMMIT appeared despite the crash"
        with open(os.path.join(tmp, "result.json"), "w") as f:
            json.dump({"ok": True, "error_surfaced": err}, f)


def scenario_persist_incr_train(pid, n, tmp):
    """Phase A of the incremental-persist crash test: train on the
    cross-process mesh, persist a full base + per-process delta shards,
    record the expected local shard bytes, drop uncommitted junk, then
    SIGKILL every process (the crash). Phase B (`persist_incr_restore`)
    runs in FRESH processes."""
    import signal

    import numpy as np
    import openembedding_tpu as embed
    from jax.experimental import multihost_utils
    from openembedding_tpu.parallel import make_mesh, multihost
    from openembedding_tpu.persist import (IncrementalPersister, list_deltas,
                                           list_persists)

    mesh = make_mesh()
    trainer = build_trainer(mesh)
    gb = 32
    batches = [multihost.global_batch(
        local_slice(make_global_batch(s, gb), pid, n), mesh)
        for s in range(4)]
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    root = os.path.join(tmp, "persists")
    with IncrementalPersister(trainer, trainer.model, root,
                              policy=embed.PersistPolicy(every_steps=1),
                              full_every=100, commit_timeout=300.0) as p:
        for b in batches:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()
    multihost_utils.sync_global_devices("incr_committed")

    fulls = [s for s, _ in list_persists(root)]
    deltas = [s for s, _ in list_deltas(root)]
    assert fulls == [1], fulls
    assert deltas == [2, 3, 4], deltas
    for _, dpath in list_deltas(root):
        for pidx in range(n):
            assert os.path.exists(os.path.join(
                dpath, f"table_categorical.p{pidx}.npz")), dpath

    # expected bytes: this process's local shards of every table array
    expect = {}
    for name, ts in state.tables.items():
        for sh in ts.weights.addressable_shards:
            expect[f"{name}/w/{sh.device.id}"] = np.asarray(sh.data)
        for k, v in ts.slots.items():
            for sh in v.addressable_shards:
                expect[f"{name}/s_{k}/{sh.device.id}"] = np.asarray(sh.data)
    np.savez(os.path.join(tmp, f"expected_p{pid}.npz"), **expect)

    if pid == 0:
        # crash-mid-write junk: an uncommitted delta dir and a stale .writing
        # dir; the restore in phase B must ignore both
        junk = os.path.join(root, "delta_000000000099")
        os.makedirs(junk, exist_ok=True)
        with open(os.path.join(junk, "meta.json"), "w") as f:
            f.write("{\"format\": \"oetpu-delta-v1\", \"parent\": 4")  # torn
        os.makedirs(os.path.join(root, "delta_000000000100.writing"),
                    exist_ok=True)
    multihost_utils.sync_global_devices("incr_expected_saved")
    log(pid, "SIGKILL (simulated crash)")
    os.kill(os.getpid(), signal.SIGKILL)


def build_hash_trainer(mesh):
    """Hashed (2^40-id-space) DeepFM — the flagship hash-table config; delta
    replay goes through the sharded find-or-insert admission kernel."""
    import dataclasses

    import openembedding_tpu as embed
    from openembedding_tpu.initializers import Constant
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.parallel import MeshTrainer

    model = make_deepfm(vocabulary=-1, dim=4, hidden=(8,), hashed=True,
                        capacity=4096)
    model.specs["categorical"] = dataclasses.replace(
        model.specs["categorical"], initializer=Constant(0.0))
    return MeshTrainer(model, embed.Adagrad(learning_rate=0.1), mesh=mesh,
                       seed=0)


def make_hash_batch(step, gb):
    import numpy as np
    rng = np.random.default_rng(300 + step)
    ids = rng.integers(0, 1 << 40, size=(gb, 3)).astype(np.int64)
    label = (rng.random(gb) < 0.5).astype(np.float32)
    return {"sparse": {"categorical": ids}, "dense": None, "label": label}


def _hash_pull(trainer, state, ids64):
    """Rows for sorted unique ids via the sharded lookup (slot layouts may
    differ between live insertion order and replay order; VALUES by id are
    the invariant)."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from openembedding_tpu.parallel.sharded import sharded_lookup

    spec = trainer.model.specs["categorical"]
    pull = jax.jit(jax.shard_map(
        partial(sharded_lookup, spec, axis=trainer.axis),
        mesh=trainer.mesh,
        in_specs=(trainer._table_pspec(spec), P()),
        out_specs=P(), check_vma=False))
    return np.asarray(pull(state.tables["categorical"], jnp.asarray(ids64)))


def scenario_persist_incr_hash_train(pid, n, tmp):
    """Hash-table variant of the incremental crash scenario: train, persist
    base+deltas, record pulled rows for the touched-id union, SIGKILL."""
    import signal

    import numpy as np
    import openembedding_tpu as embed
    from jax.experimental import multihost_utils
    from openembedding_tpu.parallel import make_mesh, multihost
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    mesh = make_mesh()
    trainer = build_hash_trainer(mesh)
    gb = 24
    batches = [multihost.global_batch(
        local_slice(make_hash_batch(s, gb), pid, n), mesh)
        for s in range(4)]
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    root = os.path.join(tmp, "persists")
    with IncrementalPersister(trainer, trainer.model, root,
                              policy=embed.PersistPolicy(every_steps=1),
                              full_every=100, commit_timeout=300.0) as p:
        for b in batches:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()
    multihost_utils.sync_global_devices("hash_committed")
    assert [s for s, _ in list_deltas(root)] == [2, 3, 4]

    ids = np.unique(np.concatenate(
        [make_hash_batch(s, gb)["sparse"]["categorical"].reshape(-1)
         for s in range(4)]))
    rows = _hash_pull(trainer, state, ids)
    if pid == 0:
        np.savez(os.path.join(tmp, "expected_rows.npz"), ids=ids, rows=rows)
    multihost_utils.sync_global_devices("hash_expected_saved")
    log(pid, "SIGKILL (simulated crash)")
    os.kill(os.getpid(), signal.SIGKILL)


def scenario_persist_incr_hash_restore(pid, n, tmp):
    """Fresh processes restore the hash model; pulled rows for the touched
    union must match what phase A recorded."""
    import numpy as np
    from jax.experimental import multihost_utils
    from openembedding_tpu.parallel import make_mesh, multihost
    from openembedding_tpu.persist import restore_server_model

    mesh = make_mesh()
    trainer = build_hash_trainer(mesh)
    gb = 24
    b = multihost.global_batch(
        local_slice(make_hash_batch(0, gb), pid, n), mesh)
    state = trainer.init(b)
    root = os.path.join(tmp, "persists")
    state = restore_server_model(state, trainer.model, root, trainer=trainer)
    assert int(state.step) == 4, int(state.step)
    with np.load(os.path.join(tmp, "expected_rows.npz")) as z:
        ids, want = z["ids"], z["rows"]
    got = _hash_pull(trainer, state, ids)
    np.testing.assert_array_equal(got, want)
    multihost_utils.sync_global_devices("hash_restore_verified")
    if pid == 0:
        with open(os.path.join(tmp, "result.json"), "w") as f:
            json.dump({"ok": True, "rows_checked": int(ids.size)}, f)


def scenario_persist_incr_restore(pid, n, tmp):
    """Phase B: fresh processes restore base+deltas; every local shard must
    be bit-identical to what phase A recorded before the SIGKILL."""
    import numpy as np
    from jax.experimental import multihost_utils
    from openembedding_tpu.parallel import make_mesh, multihost
    from openembedding_tpu.persist import restore_server_model

    mesh = make_mesh()
    trainer = build_trainer(mesh)
    gb = 32
    b = multihost.global_batch(
        local_slice(make_global_batch(0, gb), pid, n), mesh)
    state = trainer.init(b)
    root = os.path.join(tmp, "persists")
    state = restore_server_model(state, trainer.model, root, trainer=trainer)
    assert int(state.step) == 4, int(state.step)

    with np.load(os.path.join(tmp, f"expected_p{pid}.npz")) as z:
        expect = {k: z[k] for k in z.files}
    checked = 0
    for name, ts in state.tables.items():
        for sh in ts.weights.addressable_shards:
            np.testing.assert_array_equal(
                np.asarray(sh.data), expect[f"{name}/w/{sh.device.id}"])
            checked += 1
        for k, v in ts.slots.items():
            for sh in v.addressable_shards:
                np.testing.assert_array_equal(
                    np.asarray(sh.data), expect[f"{name}/s_{k}/{sh.device.id}"])
                checked += 1
    assert checked > 0
    multihost_utils.sync_global_devices("incr_restore_verified")
    if pid == 0:
        with open(os.path.join(tmp, "result.json"), "w") as f:
            json.dump({"ok": True, "shards_checked": checked}, f)


def main():
    scenario, pid, n, port, tmp = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), sys.argv[4], sys.argv[5])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    import jax
    jax.config.update("jax_enable_x64", True)
    from openembedding_tpu.parallel import multihost
    multihost.initialize(f"127.0.0.1:{port}", n, pid)
    assert jax.process_count() == n, (jax.process_count(), n)
    assert multihost.num_hosts() == n and multihost.host_id() == pid
    log(pid, f"initialized: {len(jax.devices())} global devices")
    {"train_ckpt": scenario_train_ckpt,
     "persist_ok": scenario_persist_ok,
     "persist_kill": scenario_persist_kill,
     "persist_incr_train": scenario_persist_incr_train,
     "persist_incr_restore": scenario_persist_incr_restore,
     "persist_incr_hash_train": scenario_persist_incr_hash_train,
     "persist_incr_hash_restore": scenario_persist_incr_hash_restore}[
        scenario](pid, n, tmp)
    log(pid, "done")


if __name__ == "__main__":
    main()
