"""MicroBatcher concurrency semantics, pinned directly (no HTTP in the loop).

Until now these behaviors were only exercised indirectly through handler
tests: N threads with mixed group keys must merge ONLY structurally identical
requests, a poisoned group must deliver its error to exactly its own members,
and the early-wake-on-full path (`max_batch`) must fire without waiting out
the window. Also pins the wait/occupancy metrics
(`serving.batch_wait_ms`/`serving.batch_fill_ratio`) published for tuning the
`window_ms` knob from /metrics.
"""

import concurrent.futures
import threading
import time

import numpy as np
import pytest

from openembedding_tpu.serving import MicroBatcher
from openembedding_tpu.utils import metrics

POISON = 666


class FakeModel:
    """Deterministic per-row 'predict' that records every device call.
    Output row = sum of the row's ids, so each client's slice is checkable
    regardless of how requests were merged. A batch containing POISON raises.
    """

    def __init__(self):
        self.calls = []
        self._lock = threading.Lock()

    def predict(self, batch):
        ids = np.asarray(batch["sparse"]["f"])
        with self._lock:
            self.calls.append({
                "rows": int(ids.shape[0]),
                "width": int(ids.shape[1]),
                "features": tuple(sorted(batch["sparse"])),
            })
        if (ids == POISON).any():
            raise RuntimeError("poisoned batch")
        out = ids.sum(axis=1).astype(np.float32)
        for k in sorted(batch["sparse"]):
            if k != "f":
                out = out + np.asarray(batch["sparse"][k]).sum(axis=1)
        return out


def _batch(ids, extra=None):
    b = {"sparse": {"f": np.asarray(ids, np.int64)}}
    if extra is not None:
        b["sparse"]["g"] = np.asarray(extra, np.int64)
    return b


def _expected(b):
    out = np.asarray(b["sparse"]["f"]).sum(axis=1).astype(np.float32)
    if "g" in b["sparse"]:
        out = out + np.asarray(b["sparse"]["g"]).sum(axis=1)
    return out


def test_mixed_group_keys_merge_only_structural_twins():
    """3 structure classes fired from 9 threads inside one window: same-width
    same-feature-set requests merge, everything else stays apart, and every
    client gets ITS OWN correct slice."""
    model = FakeModel()
    mb = MicroBatcher(manager=None, window_ms=250.0)
    rng = np.random.default_rng(0)
    reqs = []
    for i in range(3):  # class A: width 2, feature {f}
        reqs.append(_batch(rng.integers(0, 50, (2, 2))))
    for i in range(3):  # class B: width 3, feature {f}
        reqs.append(_batch(rng.integers(0, 50, (2, 3))))
    for i in range(3):  # class C: width 2, features {f, g}
        reqs.append(_batch(rng.integers(0, 50, (2, 2)),
                           extra=rng.integers(0, 50, (2, 2))))

    with concurrent.futures.ThreadPoolExecutor(len(reqs)) as ex:
        outs = list(ex.map(lambda b: mb.predict(model, "m", b), reqs))

    for b, out in zip(reqs, outs):
        np.testing.assert_allclose(np.asarray(out), _expected(b))
    # merging happened within classes, never across them
    assert len(model.calls) < len(reqs)
    for call in model.calls:
        assert (call["width"], call["features"]) in [
            (2, ("f",)), (3, ("f",)), (2, ("f", "g"))]
    merged_rows = sum(c["rows"] for c in model.calls)
    assert merged_rows == sum(np.asarray(b["sparse"]["f"]).shape[0]
                              for b in reqs)  # nothing dropped or duplicated


def test_poisoned_group_fails_alone():
    """A group whose merged batch raises delivers that error to exactly its
    own members; the structurally different group is untouched."""
    model = FakeModel()
    mb = MicroBatcher(manager=None, window_ms=250.0)
    good = [_batch(np.full((2, 3), 7)) for _ in range(2)]
    bad = [_batch([[1, POISON]]), _batch([[2, 3]])]  # width 2: one group

    with concurrent.futures.ThreadPoolExecutor(4) as ex:
        good_f = [ex.submit(mb.predict, model, "m", b) for b in good]
        bad_f = [ex.submit(mb.predict, model, "m", b) for b in bad]
        for f in good_f:
            np.testing.assert_allclose(np.asarray(f.result(timeout=30)),
                                       [21.0, 21.0])
        for f in bad_f:
            with pytest.raises(RuntimeError, match="poisoned"):
                f.result(timeout=30)


def test_internally_ragged_request_fails_alone_at_enqueue():
    """A request whose OWN features disagree on the row count raises before
    it ever joins a group (never poisoning groupmates)."""
    from openembedding_tpu.export import RaggedBatchError
    model = FakeModel()
    mb = MicroBatcher(manager=None, window_ms=50.0)
    ragged = {"sparse": {"f": np.zeros((2, 2), np.int64),
                         "g": np.zeros((3, 2), np.int64)}}
    with pytest.raises(RaggedBatchError):
        mb.predict(model, "m", ragged)
    assert model.calls == []  # never reached the device


def test_early_wake_on_max_batch():
    """A group reaching `max_batch` rows wakes the leader immediately — the
    requests complete far inside the (deliberately huge) window."""
    model = FakeModel()
    mb = MicroBatcher(manager=None, window_ms=30_000.0, max_batch=8)
    reqs = [_batch(np.full((4, 2), i)) for i in range(2)]  # 8 rows total
    t0 = time.monotonic()
    with concurrent.futures.ThreadPoolExecutor(2) as ex:
        outs = list(ex.map(lambda b: mb.predict(model, "m", b), reqs))
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, "leader slept out the window despite a full group"
    for b, out in zip(reqs, outs):
        np.testing.assert_allclose(np.asarray(out), _expected(b))


def test_batcher_publishes_wait_and_fill_metrics():
    """serving.batch_wait_ms / serving.batch_fill_ratio accumulate per merged
    call, next to the existing predict_batches/predict_requests counters, so
    window_ms is tunable from /metrics."""
    model = FakeModel()
    mb = MicroBatcher(manager=None, window_ms=30.0, max_batch=64)
    wait = metrics.Accumulator.get("serving.batch_wait_ms", "avg")
    fill = metrics.Accumulator.get("serving.batch_fill_ratio", "avg")
    w0, f0 = wait.count, fill.count
    with concurrent.futures.ThreadPoolExecutor(3) as ex:
        list(ex.map(lambda b: mb.predict(model, "m", b),
                    [_batch(np.full((2, 2), i)) for i in range(3)]))
    assert wait.count > w0
    assert fill.count > f0
    assert 0.0 < fill.value() <= 1.0
    assert wait.value() >= 0.0
