"""Native (C++) Criteo pipeline: build, parity vs the Python reader, preprocess.

The contract is bit-identical sparse ids/labels and float-rounding-identical dense
features vs `data.criteo.read_criteo_tsv(native="off")` (the checked oracle), plus
the frequency-relabel tool (reference `test/criteo_preprocess.cpp`)."""

import shutil

import numpy as np
import pytest

from openembedding_tpu.data.criteo import (NUM_DENSE, NUM_SPARSE,
                                           read_criteo_tsv)

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ compiler")


def _write_tsv(path, rows, seed=0, short_rows=False):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for r in range(rows):
            cols = [str(rng.integers(0, 2))]
            for i in range(NUM_DENSE):
                if rng.random() < 0.1:
                    cols.append("")
                else:
                    cols.append(str(int(rng.integers(-5, 1000))))
            n_cat = NUM_SPARSE if not short_rows or rng.random() < 0.7 else \
                int(rng.integers(0, NUM_SPARSE))
            for i in range(n_cat):
                if rng.random() < 0.1:
                    cols.append("")
                else:
                    cols.append(f"{int(rng.integers(0, 1 << 32)):08x}")
            f.write("\t".join(cols) + "\n")
    return path


@pytest.fixture(scope="module")
def native():
    from openembedding_tpu import native as native_mod
    native_mod.build()
    return native_mod


def _collect(it):
    batches = list(it)
    if not batches:
        return None
    return {
        "label": np.concatenate([b["label"] for b in batches]),
        "dense": np.concatenate([b["dense"] for b in batches]),
        "sparse": np.concatenate([b["sparse"]["categorical"] for b in batches]),
    }


def test_hash_parity(native):
    from openembedding_tpu.data.criteo import hash_category
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 1 << 62, size=100, dtype=np.uint64)
    fields = rng.integers(0, NUM_SPARSE, size=100, dtype=np.uint64)
    want = hash_category(toks, fields, 1 << 25)
    lib = native.load()
    got = np.asarray([lib.oetpu_hash_category(int(t), int(f), 1 << 25)
                      for t, f in zip(toks, fields)])
    np.testing.assert_array_equal(want, got)


@pytest.mark.parametrize("short_rows", [False, True])
def test_reader_parity(native, tmp_path, short_rows):
    path = _write_tsv(str(tmp_path / "a.tsv"), 257, short_rows=short_rows)
    kw = dict(id_space=1 << 20, drop_remainder=False)
    want = _collect(read_criteo_tsv(path, 64, native="off", **kw))
    got = _collect(read_criteo_tsv(path, 64, native="on", **kw))
    np.testing.assert_array_equal(want["label"], got["label"])
    np.testing.assert_array_equal(want["sparse"], got["sparse"])
    np.testing.assert_allclose(want["dense"], got["dense"], rtol=1e-6)


def test_reader_multi_file_and_hosts(native, tmp_path):
    p1 = _write_tsv(str(tmp_path / "a.tsv"), 100, seed=1)
    p2 = _write_tsv(str(tmp_path / "b.tsv"), 117, seed=2)
    for host_id in (0, 2):
        kw = dict(id_space=1 << 20, drop_remainder=False,
                  host_id=host_id, num_hosts=3)
        want = _collect(read_criteo_tsv([p1, p2], 32, native="off", **kw))
        got = _collect(read_criteo_tsv([p1, p2], 32, native="on", **kw))
        np.testing.assert_array_equal(want["label"], got["label"])
        np.testing.assert_array_equal(want["sparse"], got["sparse"])


def test_reader_drop_remainder_and_repeat(native, tmp_path):
    path = _write_tsv(str(tmp_path / "c.tsv"), 70)
    batches = list(read_criteo_tsv(path, 32, native="on", drop_remainder=True))
    assert len(batches) == 2  # 70 rows -> 2 full batches, 6 dropped
    it = read_criteo_tsv(path, 32, native="on", drop_remainder=True, repeat=True)
    seen = [next(it) for _ in range(5)]  # crosses the epoch boundary
    np.testing.assert_array_equal(seen[0]["sparse"]["categorical"],
                                  seen[2]["sparse"]["categorical"])


def test_missing_trailing_fields_match(native, tmp_path):
    # a row with ONLY the label: every dense -> 0-transform, cat i -> hash(i)
    path = str(tmp_path / "d.tsv")
    with open(path, "w") as f:
        f.write("1\n")
        f.write("0\t" + "\t".join(["3"] * NUM_DENSE) + "\n")
    kw = dict(id_space=1 << 20, drop_remainder=False)
    want = _collect(read_criteo_tsv(path, 4, native="off", **kw))
    got = _collect(read_criteo_tsv(path, 4, native="on", **kw))
    np.testing.assert_array_equal(want["sparse"], got["sparse"])
    np.testing.assert_allclose(want["dense"], got["dense"], rtol=1e-6)


def test_preprocess_relabel(native, tmp_path):
    src = str(tmp_path / "raw.tsv")
    with open(src, "w") as f:
        # c0 token "aa" x3, "bb" x2, "cc" x1 -> ranks aa=1, bb=2, cc=rare(0)
        for tok in ["aa", "aa", "aa", "bb", "bb", "cc"]:
            cols = ["1"] + ["2"] * NUM_DENSE + [tok] + ["ff"] * (NUM_SPARSE - 1)
            f.write("\t".join(cols) + "\n")
    dst = str(tmp_path / "relabel.tsv")
    vocab = native.preprocess(src, dst, min_count=2)
    assert vocab[0] == 3   # {0 rare, 1 aa, 2 bb}
    assert vocab[1] == 2   # {0 rare, 1 ff}
    col0 = [line.split("\t")[1 + NUM_DENSE] for line in open(dst)]
    assert col0 == ["1", "1", "1", "2", "2", "0"]
    # non-categorical columns pass through
    first = open(dst).readline().split("\t")
    assert first[0] == "1" and first[1] == "2"


def test_unreadable_file_is_an_error_not_a_skip(native, tmp_path):
    """fopen failure must surface as IOError (the Python reader raises too);
    silently training on a subset would violate the parity contract."""
    import ctypes
    lib = native.load()
    missing = str(tmp_path / "nope.tsv").encode()
    arr = (ctypes.c_char_p * 1)(missing)
    handle = lib.oetpu_reader_create(arr, 1, 8, 1 << 20, 0, 1, 2)
    try:
        labels = np.empty((8,), np.float32)
        dense = np.empty((8, NUM_DENSE), np.float32)
        sparse = np.empty((8, NUM_SPARSE), np.int64)
        assert lib.oetpu_reader_next(handle, labels, dense, sparse) == -1
    finally:
        lib.oetpu_reader_destroy(handle)


def test_long_line_spanning_reads(native, tmp_path):
    """A line longer than one IO chunk exercises the carry path."""
    path = str(tmp_path / "long.tsv")
    filler = "f" * (1 << 21)  # 2 MB token > 1 MB chunk
    with open(path, "w") as f:
        cols = ["1"] + ["2"] * NUM_DENSE + [filler] + ["aa"] * (NUM_SPARSE - 1)
        f.write("\t".join(cols) + "\n")
        cols2 = ["0"] + ["3"] * NUM_DENSE + ["bb"] * NUM_SPARSE
        f.write("\t".join(cols2) + "\n")
    kw = dict(id_space=1 << 20, drop_remainder=False)
    want = _collect(read_criteo_tsv(path, 4, native="off", **kw))
    got = _collect(read_criteo_tsv(path, 4, native="on", **kw))
    np.testing.assert_array_equal(want["sparse"], got["sparse"])
    np.testing.assert_array_equal(want["label"], got["label"])


def test_native_reader_throughput_smoke(native, tmp_path):
    """Not a benchmark, just proof the multi-threaded path moves real volume."""
    path = _write_tsv(str(tmp_path / "big.tsv"), 5000, seed=3)
    total = sum(b["label"].shape[0]
                for b in read_criteo_tsv(path, 512, native="on",
                                         drop_remainder=False))
    assert total == 5000


def test_native_reads_gzip_tsv(tmp_path):
    """Criteo-1TB ships day_*.gz: the native reader inflates through zlib and
    matches both its own plain-file output and the Python gzip path."""
    import gzip

    from openembedding_tpu.data.criteo import read_criteo_tsv
    from openembedding_tpu.native import NativeCriteoReader

    plain = tmp_path / "day.tsv"
    rows = []
    rng = np.random.default_rng(5)
    for i in range(100):
        dense = "\t".join(str(int(x)) for x in rng.integers(0, 50, 13))
        cats = "\t".join(f"{int(x):x}" for x in rng.integers(0, 1 << 20, 26))
        rows.append(f"{int(rng.integers(0, 2))}\t{dense}\t{cats}")
    plain.write_text("\n".join(rows) + "\n")
    gz = tmp_path / "day.tsv.gz"
    with gzip.open(gz, "wt") as f:
        f.write(plain.read_text())

    def collect(it):
        return [(b["label"].copy(),
                 np.asarray(b["dense"]).copy(),
                 np.asarray(b["sparse"]["categorical"]).copy()) for b in it]

    kw = dict(id_space=1 << 22, drop_remainder=False)
    want = collect(NativeCriteoReader([str(plain)], 32, **kw))
    got = collect(NativeCriteoReader([str(gz)], 32, **kw))
    py = collect(read_criteo_tsv([str(gz)], 32, native="off", **kw))
    assert len(got) == len(want) == len(py) == 4
    for g, w, p in zip(got, want, py):
        for a, b, c in zip(g, w, p):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
