"""Remote-storage URIs (`utils/fs.py`): scheme registry, shell-pipe streams
(the reference's `hadoop fs -cat |` transport, `EmbeddingShardFile.h`),
URI data streaming in `read_criteo_tsv`, and checkpoint save/load through a
registered scheme."""

import os

import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.data import read_criteo_tsv, synthetic_criteo
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.utils import fs as fsmod

TSV = os.path.join(os.path.dirname(__file__), "..", "examples", "train100.tsv")


class DirFS(fsmod.FileSystemBase):
    """Test double: `mock://x` -> files under a local root (fsspec-shaped)."""

    def __init__(self, root):
        self.root = root

    def _p(self, uri):
        return os.path.join(self.root, uri.split("://", 1)[1])

    def open(self, uri, mode="rb"):
        os.makedirs(os.path.dirname(self._p(uri)), exist_ok=True)
        return open(self._p(uri), mode)

    def exists(self, uri):
        return os.path.exists(self._p(uri))

    def listdir(self, uri):
        return sorted(os.listdir(self._p(uri)))

    def makedirs(self, uri):
        os.makedirs(self._p(uri), exist_ok=True)

    def isdir(self, uri):
        return os.path.isdir(self._p(uri))


@pytest.fixture()
def mockfs(tmp_path):
    fs = DirFS(str(tmp_path / "remote"))
    fsmod.register_filesystem("mock", fs)
    yield fs
    fsmod._REGISTRY.pop("mock", None)


def test_split_and_resolve(mockfs):
    assert fsmod.split_uri("/a/b") == (None, "/a/b")
    assert fsmod.split_uri("file:///a/b") == (None, "/a/b")
    assert fsmod.split_uri("mock://x/y") == ("mock", "mock://x/y")
    assert not fsmod.is_remote("/a/b")
    assert fsmod.is_remote("mock://x")
    with pytest.raises(ValueError, match="no filesystem registered"):
        fsmod.resolve("unknown://x")


def test_shell_pipe_fs_round_trip(tmp_path):
    """A ShellPipeFS over plain sh commands proves the pipe transport the
    hadoop registration uses (hadoop itself is absent in this image)."""
    root = tmp_path / "shellfs"
    root.mkdir()
    fs = fsmod.ShellPipeFS(
        cat=["cat", "{path}"],
        put=["sh", "-c", "mkdir -p $(dirname {path}) && cat > {path}"],
        test=["test", "-e", "{path}"],
        ls=["ls", "{path}"],
        mkdir=["mkdir", "-p", "{path}"],
        testdir=["test", "-d", "{path}"],
    )
    p = str(root / "a" / "blob.bin")
    payload = os.urandom(1 << 16)
    with fs.open(p, "wb") as f:
        f.write(payload)
    assert fs.exists(p)
    with fs.open(p, "rb") as f:
        assert f.read() == payload
    assert fs.listdir(str(root / "a")) == ["blob.bin"]
    assert fs.isdir(str(root / "a")) and not fs.isdir(p)


def test_hdfs_scheme_registered():
    fs, _ = fsmod.resolve("hdfs://nn/path")
    assert isinstance(fs, fsmod.ShellPipeFS)
    assert fs._cmd("cat", "hdfs://nn/p")[-1] == "hdfs://nn/p"


def test_read_criteo_tsv_from_uri(mockfs):
    """The Criteo stream reads straight off a URI (no staging, no native)."""
    with open(TSV, "rb") as f:
        data = f.read()
    with mockfs.open("mock://data/train.tsv", "wb") as f:
        f.write(data)
    local = list(read_criteo_tsv([TSV], 32, id_space=1 << 20))
    remote = list(read_criteo_tsv(["mock://data/train.tsv"], 32,
                                  id_space=1 << 20))
    assert len(local) == len(remote)
    for a, b in zip(local, remote):
        np.testing.assert_array_equal(a["sparse"]["categorical"],
                                      b["sparse"]["categorical"])
        np.testing.assert_array_equal(a["label"], b["label"])
    with pytest.raises(ValueError, match="local files only"):
        next(read_criteo_tsv(["mock://data/train.tsv"], 32, native="on"))


def test_checkpoint_through_uri(mockfs):
    """Trainer.save/load against a mock:// URI: write-local + push, then
    fetch + load — rows identical to a local round trip."""
    model = make_deepfm(vocabulary=512, dim=4, hidden=(8,))
    tr = Trainer(model, embed.Adagrad(learning_rate=0.1))
    b = next(synthetic_criteo(16, id_space=512, steps=1, seed=0))
    st = tr.init(b)
    st, _ = tr.jit_train_step()(st, b)
    tr.save(st, "mock://ckpts/run1")
    assert mockfs.exists("mock://ckpts/run1/model_meta")
    assert mockfs.exists("mock://ckpts/run1/variable_0/weights.npy")

    tr2 = Trainer(make_deepfm(vocabulary=512, dim=4, hidden=(8,)),
                  embed.Adagrad(learning_rate=0.1))
    st2 = tr2.init(b)
    st2 = tr2.load(st2, "mock://ckpts/run1")
    np.testing.assert_array_equal(
        np.asarray(st2.tables["categorical"].weights),
        np.asarray(st.tables["categorical"].weights))


def test_serving_loads_from_uri(mockfs):
    """ShardedModel/StandaloneModel load remote checkpoints via staging."""
    from openembedding_tpu.export import StandaloneModel, export_standalone
    from openembedding_tpu.parallel.serving import ShardedModel

    model = make_deepfm(vocabulary=512, dim=4, hidden=(8,))
    tr = Trainer(model, embed.Adagrad(learning_rate=0.1))
    b = next(synthetic_criteo(16, id_space=512, steps=1, seed=2))
    st = tr.init(b)
    st, _ = tr.jit_train_step()(st, b)
    tr.save(st, "mock://serve/ck")
    sm = ShardedModel.load("mock://serve/ck")
    want = np.asarray(st.tables["categorical"].weights)[[0, 3, 7]]
    np.testing.assert_allclose(
        np.asarray(sm.lookup("categorical", np.asarray([0, 3, 7]))), want,
        rtol=1e-6, atol=1e-6)

    import tempfile
    exp = tempfile.mkdtemp()
    export_standalone(st, model, exp)
    fsmod.stage_out(exp, "mock://serve/exp")
    sa = StandaloneModel.load("mock://serve/exp")
    np.testing.assert_allclose(
        np.asarray(sa.lookup("categorical", np.asarray([0, 3, 7]))), want,
        rtol=1e-6, atol=1e-6)


def test_early_abandoned_pipe_reader_is_quiet(tmp_path):
    """Breaking out of a URI stream early (islice'd loops) must not raise —
    the producer is terminated quietly; real failures still raise."""
    fs = fsmod.ShellPipeFS(
        cat=["cat", "{path}"], put=["sh", "-c", "cat > {path}"],
        test=["test", "-e", "{path}"], ls=["ls", "{path}"],
        mkdir=["mkdir", "-p", "{path}"])
    big = tmp_path / "big.bin"
    big.write_bytes(os.urandom(1 << 20))
    r = fs.open(str(big), "rb")
    r.read(1024)
    r.close()  # abandoned mid-stream: no raise
    # a failing producer DOES raise at close
    bad = fs.open(str(tmp_path / "missing.bin"), "rb")
    data = bad.read()
    assert data == b""
    with pytest.raises(IOError, match="rc="):
        bad.close()


def test_sharded_checkpoint_through_uri(mockfs):
    """MeshTrainer per-shard streaming dump pushes through the adapter and
    reloads at a different mesh size."""
    import jax
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    model = make_deepfm(vocabulary=512, dim=4, hidden=(8,))
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh())
    b = next(synthetic_criteo(16, id_space=512, steps=1, seed=1))
    st = tr.init(b)
    st, _ = tr.jit_train_step(b, st)(st, b)
    tr.save(st, "mock://ckpts/sharded1")
    assert mockfs.exists(
        "mock://ckpts/sharded1/variable_0/shard_00000_of_00008/weights.npy")

    tr2 = Trainer(make_deepfm(vocabulary=512, dim=4, hidden=(8,)),
                  embed.Adagrad(learning_rate=0.1))
    st2 = tr2.init(b)
    st2 = tr2.load(st2, "mock://ckpts/sharded1")  # 8 -> 1 reshard via URI
    from openembedding_tpu.parallel.sharded import deinterleave_rows
    want = np.asarray(deinterleave_rows(
        np.asarray(st.tables["categorical"].weights), 8, 512))
    np.testing.assert_allclose(
        np.asarray(st2.tables["categorical"].weights)[:512], want,
        rtol=0, atol=0)
