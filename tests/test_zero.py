"""ZeRO dense-state sharding (round 14): `MeshTrainer(dense_shard=True)`
replaces the dense-grad psum + replicated optimizer apply with
reduce_scatter -> 1/S local opt-state shard update -> all_gather
(`parallel/zero.py`, arXiv:2004.13336).

Acceptance (ISSUE 10):
- fp32 training is BIT-exact vs the replicated baseline: losses, dense
  params and (externalized) optimizer slots after N steps, per optimizer;
- on-disk artifacts — sharded checkpoint, standalone export, incremental
  sync deltas — are byte-identical to a ZeRO-off control run (the
  `externalize` hook unshards before every writer);
- checkpoints are cross-compatible: a ZeRO-off dump loads into a ZeRO-on
  trainer (and vice versa) and training continues bit-exact;
- the flat layout round-trips bitwise and the scalar-slot invariant is
  enforced at conversion time.
"""

import os

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.model import EmbeddingModel
from openembedding_tpu.parallel import MeshTrainer, make_mesh
from openembedding_tpu.parallel import zero
from openembedding_tpu.utils import metrics

S = 8  # conftest forces 8 virtual CPU devices
B = 64
VOCAB = 256


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics._REGISTRY.clear()
    yield
    metrics._REGISTRY.clear()


class _Tower(nn.Module):
    """Vector + matrix + scalar dense params: exercises multi-leaf flatten
    offsets, and Adam's scalar beta-power slots ride the scalar path."""

    @nn.compact
    def __call__(self, embedded, dense):
        bias = self.param("bias", nn.initializers.zeros, (1,), jnp.float32)
        w = self.param("w", nn.initializers.normal(0.02), (8, 4), jnp.float32)
        out = jnp.sum(embedded["a"].astype(jnp.float32) @ w, axis=(1, 2))
        out = out + jnp.sum(embedded["b"].astype(jnp.float32), axis=(1, 2))
        return out + bias[0]


def _model():
    return EmbeddingModel(_Tower(), [
        embed.Embedding(VOCAB, 8, name="a"),
        embed.Embedding(-1, 8, name="b", capacity=4096),
    ])


def _batches(n, seed=1):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        a = rng.integers(0, VOCAB, (B, 4)).astype(np.int32)
        b = rng.integers(0, 1 << 40, (B, 3)).astype(np.int64)
        out.append({"sparse": {"a": a, "b": b},
                    "label": rng.integers(0, 2, (B,)).astype(np.float32)})
    return out


def _trees_bitwise_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


# ---------------------------------------------------------------------------
# fp32 bit-parity: sharded update == replicated update
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("make_opt", [
    lambda: embed.Adagrad(learning_rate=0.1),
    lambda: embed.Adam(learning_rate=0.01),
], ids=["adagrad", "adam"])
def test_zero_fp32_bit_parity(make_opt):
    """THE acceptance pin: 4 steps with dense_shard on vs off — losses,
    dense params, and externalized optimizer slots all bitwise equal
    (psum_scatter is bit-identical to psum-then-slice on a fixed mesh,
    and the per-chunk optimizer math is elementwise)."""
    def run(dense_shard):
        batches = _batches(4)
        tr = MeshTrainer(_model(), make_opt(), mesh=make_mesh(),
                         wire="fp32", dense_shard=dense_shard)
        state = tr.init(batches[0])
        if dense_shard:
            assert zero.is_sharded_slots(state.dense_slots)
        step = tr.jit_train_step(batches[0], state)
        losses = []
        for b in batches:
            state, m = step(state, b)
            losses.append(np.asarray(m["loss"]).tobytes())
        return tr.externalize(state), losses

    s0, l0 = run(False)
    s1, l1 = run(True)
    assert l0 == l1
    _trees_bitwise_equal(s0.dense_params, s1.dense_params)
    _trees_bitwise_equal(s0.dense_slots, s1.dense_slots)


def test_zero_shard_unshard_round_trip():
    """dense_to_sharded -> dense_to_replicated is byte-identical, and the
    sharded form is the flat `{__zero__: ...}` layout with per-shard chunks."""
    batches = _batches(1)
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), dense_shard=True)
    state = tr.init(batches[0])
    assert zero.is_sharded_slots(state.dense_slots)
    plan = tr._zero_plan
    assert plan.num_shards == S
    assert plan.padded == plan.chunk * S >= plan.total
    flat = state.dense_slots[zero.ZERO_KEY]
    for k, v in flat.items():
        assert v.shape == ((1, 1) if k in plan.scalar_slots
                           else (1, plan.padded))
    rep = tr.dense_to_replicated(state)
    assert not zero.is_sharded_slots(rep.dense_slots)
    back = tr.dense_to_sharded(rep)
    _trees_bitwise_equal(state.dense_slots, back.dense_slots)
    # gauges from the sharded update path are registered under dense.*
    step = tr.jit_train_step(batches[0], state)
    state, _ = step(state, batches[0])
    rep_m = metrics.report()
    assert rep_m["dense.zero_shards"] == S
    assert rep_m["dense.opt_state_bytes_per_replica"] > 0


def test_zero_single_shard_is_noop():
    """dense_shard on a 1-device mesh stays in the replicated layout (no
    collective exists to win anything; zero_enabled gates on S > 1)."""
    batches = _batches(1)
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(jax.devices()[:1]), dense_shard=True)
    assert not tr.zero_enabled
    state = tr.init(batches[0])
    assert not zero.is_sharded_slots(state.dense_slots)
    step = tr.jit_train_step(batches[0], state)
    state, m = step(state, batches[0])
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# Persistence obliviousness: checkpoint / export / deltas byte-identical
# ---------------------------------------------------------------------------


def _run_training(tmp_path, tag, *, dense_shard, dense_wire=None):
    from openembedding_tpu.export import export_standalone
    from openembedding_tpu.persist import IncrementalPersister, PersistPolicy
    batches = _batches(6, seed=7)
    tr = MeshTrainer(_model(), embed.Adam(learning_rate=0.01),
                     mesh=make_mesh(), wire="fp32", dense_shard=dense_shard,
                     dense_wire=dense_wire)
    state = tr.init(batches[0])
    step = tr.jit_train_step(batches[0], state)
    root = tmp_path / tag
    losses = []
    with IncrementalPersister(tr, tr.model, str(root / "persist"), window=1,
                              policy=PersistPolicy(every_steps=2),
                              full_every=100) as p:
        for b in batches:
            state, m = step(state, b)
            losses.append(float(m["loss"]))
            p.maybe_persist(state, batch=b)
        p.wait()
    tr.save(state, str(root / "ckpt"), model_sign="t")
    export_standalone(tr.externalize(state), tr.model, str(root / "export"),
                      model_sign="t-0")
    return losses


def _assert_trees_equal(off_root, on_root, skip=("model_meta",)):
    found = 0
    for root, _dirs, files in os.walk(off_root):
        for fn in files:
            if fn in skip:
                continue
            p_off = os.path.join(root, fn)
            p_on = p_off.replace(str(off_root), str(on_root))
            with open(p_off, "rb") as fa, open(p_on, "rb") as fb:
                assert fa.read() == fb.read(), f"differs: {p_off}"
            found += 1
    assert found > 0


def test_zero_checkpoint_export_delta_byte_identical(tmp_path):
    """A dense_shard run's on-disk artifacts — sharded checkpoint,
    standalone export, incremental sync deltas — equal a ZeRO-off control
    run's byte for byte (every writer goes through `externalize`)."""
    l_off = _run_training(tmp_path, "off", dense_shard=False)
    l_on = _run_training(tmp_path, "on", dense_shard=True)
    assert l_off == l_on
    _assert_trees_equal(tmp_path / "off" / "ckpt", tmp_path / "on" / "ckpt")
    _assert_trees_equal(tmp_path / "off" / "export",
                        tmp_path / "on" / "export",
                        skip=("model_meta", "model_meta.json"))
    import glob
    offs = sorted(glob.glob(str(tmp_path / "off" / "persist" / "**" /
                                "table_*.npz"), recursive=True))
    assert offs
    for p_off in offs:
        p_on = p_off.replace(str(tmp_path / "off"), str(tmp_path / "on"))
        a, b = np.load(p_off), np.load(p_on)
        assert sorted(a.files) == sorted(b.files), p_off
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k],
                                          err_msg=f"{p_off}:{k}")


def test_zero_checkpoint_cross_compatible(tmp_path):
    """A ZeRO-off dump loads into a ZeRO-on trainer (and vice versa), and
    continued training stays bit-exact — the serialized form is ONE layout
    (replicated), conversion happens at the load/save boundary."""
    batches = _batches(5, seed=11)

    def run(save_shard, load_shard):
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), dense_shard=save_shard)
        state = tr.init(batches[0])
        step = tr.jit_train_step(batches[0], state)
        for b in batches[:2]:
            state, _ = step(state, b)
        path = str(tmp_path / f"ckpt_{save_shard}_{load_shard}")
        tr.save(state, path, model_sign="x")
        tr2 = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                          mesh=make_mesh(), dense_shard=load_shard)
        st2 = tr2.init(batches[0])
        st2 = tr2.load(st2, path)
        if load_shard:
            assert zero.is_sharded_slots(st2.dense_slots)
        step2 = tr2.jit_train_step(batches[0], st2)
        losses = []
        for b in batches[2:]:
            st2, m = step2(st2, b)
            losses.append(np.asarray(m["loss"]).tobytes())
        return tr2.externalize(st2), losses

    s_base, l_base = run(False, False)
    for combo in ((False, True), (True, False), (True, True)):
        s, l = run(*combo)
        assert l == l_base, combo
        _trees_bitwise_equal(s_base.dense_params, s.dense_params)
        _trees_bitwise_equal(s_base.dense_slots, s.dense_slots)


# ---------------------------------------------------------------------------
# parallel/zero.py units
# ---------------------------------------------------------------------------


def _toy_plan(num_shards=4):
    params = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
              "b": jnp.asarray([7.0], jnp.float32)}
    opt = embed.Adam(learning_rate=0.01)
    return params, opt, zero.build_plan(params, opt, num_shards)


def test_zero_flatten_round_trip():
    params, _, plan = _toy_plan()
    flat = zero.flatten_tree(plan, params)
    assert flat.shape == (plan.padded,) and plan.total == 7
    back = zero.unflatten_tree(plan, flat, params)
    _trees_bitwise_equal(params, back)
    # padding lanes are zero (reduce_scatter must not see garbage)
    assert not np.asarray(flat[plan.total:]).any()


def test_zero_scalar_slot_guard():
    """Diverging scalar slots (e.g. Adam beta powers in a hand-edited
    state) must fail conversion loudly, not silently pick one leaf's."""
    params, opt, plan = _toy_plan()
    assert plan.scalar_slots  # Adam: beta powers

    def leaf_slots(p):
        return {name: (jnp.ones((1, 1), jnp.float32)
                       if name in plan.scalar_slots
                       else jnp.zeros((1, p.size), jnp.float32))
                for name in (*plan.vector_slots, *plan.scalar_slots)}

    slots = jax.tree_util.tree_map(leaf_slots, params)
    zero.check_scalar_slots_equal(plan, slots)  # equal: fine
    name = sorted(plan.scalar_slots)[0]
    slots["b"][name] = jnp.asarray([[2.0]], jnp.float32)
    with pytest.raises(ValueError, match="dense_shard"):
        zero.check_scalar_slots_equal(plan, slots)


def test_zero_rejects_wide_dtypes():
    params = {"a": jnp.zeros((3,), jnp.float64)}
    with pytest.raises(ValueError, match="f32|float64|4-byte"):
        zero.build_plan(params, embed.Adagrad(learning_rate=0.1), 4)


# ---------------------------------------------------------------------------
# round 17: quantized dense collectives (dense_wire)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt", ["bf16", "int8", "sparse_topk"])
def test_dense_wire_trains_close_to_fp32(fmt):
    """`dense_wire` swaps the fp32 psum_scatter for the in-band-encoded
    two-stage reduce (encode -> a2a partials -> per-replica fp32 sum) and
    ships the param all_gather on the bf16 carrier, one lossy step per
    gradient. The ZeRO plan aligns chunks to the codec block, int8 carries
    fp32 masters + per-chunk EF residuals as extra `__zero__` slots, and N
    steps stay within format tolerance of the lossless round-14 path."""
    from openembedding_tpu.ops import wire as wire_mod

    def run(dense_wire):
        batches = _batches(4, seed=3)
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire="fp32", dense_shard=True,
                         dense_wire=dense_wire)
        state = tr.init(batches[0])
        step = tr.jit_train_step(batches[0], state)
        losses = []
        for b in batches:
            state, m = step(state, b)
            losses.append(float(m["loss"]))
        return tr, state, losses

    tr_f, st_f, l_f = run(None)
    tr_q, st_q, l_q = run(fmt)
    plan = tr_q._zero_plan
    assert plan.chunk % wire_mod.INBAND_BLOCK == 0
    flat = st_q.dense_slots[zero.ZERO_KEY]
    assert zero.DENSE_MASTER_KEY in flat
    # int8 and sparse_topk both need error feedback (quantization bias /
    # untransmitted mass); bf16 truncation rides without. On this toy model
    # chunk == 32 so the auto top-k resolves to k == chunk: the sparse path
    # exercises the full encode -> a2a -> scatter-sum pipeline while every
    # element still ships (int8-quantized), keeping the int8 loss tier.
    assert (zero.DENSE_EF_KEY in flat) == (fmt in ("int8", "sparse_topk"))
    assert np.all(np.isfinite(l_q))
    np.testing.assert_allclose(l_q, l_f, rtol=0.02, atol=0.02)
    # externalize folds the masters back and drops the wire-only slots:
    # same tree schema as the lossless run, params within tolerance
    ext_f = tr_f.externalize(st_f)
    ext_q = tr_q.externalize(st_q)
    assert (jax.tree_util.tree_structure(ext_q.dense_slots)
            == jax.tree_util.tree_structure(ext_f.dense_slots))
    assert (jax.tree_util.tree_structure(ext_q.dense_params)
            == jax.tree_util.tree_structure(ext_f.dense_params))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=0.05, atol=0.05),
        ext_q.dense_params, ext_f.dense_params)
    # gauges: the quantized path reports a2a bytes, not a reduce_scatter
    rep = metrics.report()
    assert rep["dense.a2a_bytes"] > 0
    assert rep["dense.reduce_scatter_bytes"] == 0
    assert rep["dense.wire_bytes_per_step"] > 0


def test_dense_wire_checkpoint_cross_compatible(tmp_path):
    """The serialized form stays ONE layout (replicated fp32 — masters
    folded into dense_params, EF wire residuals dropped/reseeded): a dump
    saved under any of {replicated, ZeRO, ZeRO-bf16, ZeRO-int8,
    ZeRO-sparse} loads into any other, the loaded external state is bitwise
    the saved one, and training continues finite."""
    batches = _batches(3, seed=13)
    configs = {
        "replicated": {},
        "zero": {"dense_shard": True},
        "zero_bf16": {"dense_shard": True, "dense_wire": "bf16"},
        "zero_int8": {"dense_shard": True, "dense_wire": "int8"},
        "zero_sparse": {"dense_shard": True, "dense_wire": "sparse_topk"},
    }

    def make(cfg):
        return MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                           mesh=make_mesh(), wire="fp32", **configs[cfg])

    saved = {}
    for cfg in ("replicated", "zero_int8", "zero_sparse"):
        tr = make(cfg)
        state = tr.init(batches[0])
        step = tr.jit_train_step(batches[0], state)
        for b in batches[:2]:
            state, _ = step(state, b)
        path = str(tmp_path / cfg)
        tr.save(state, path, model_sign="x")
        saved[cfg] = (path, tr.externalize(state))

    for src, (path, ext_src) in saved.items():
        for dst in configs:
            tr2 = make(dst)
            st2 = tr2.init(batches[0])
            st2 = tr2.load(st2, path)
            if dst != "replicated":
                assert zero.is_sharded_slots(st2.dense_slots)
                flat = st2.dense_slots[zero.ZERO_KEY]
                assert ((zero.DENSE_MASTER_KEY in flat)
                        == bool(configs[dst].get("dense_wire")))
            ext2 = tr2.externalize(st2)
            _trees_bitwise_equal(ext_src.dense_params, ext2.dense_params)
            _trees_bitwise_equal(ext_src.dense_slots, ext2.dense_slots)
            step2 = tr2.jit_train_step(batches[0], st2)
            st2, m = step2(st2, batches[2])
            assert np.isfinite(float(m["loss"])), (src, dst)


@pytest.mark.parametrize("fmt", ["int8", "sparse_topk"])
def test_dense_wire_artifacts_schema_oblivious_and_reload(tmp_path, fmt):
    """A narrow-wire run (int8 or sparse_topk) writes artifacts — sharded
    checkpoint, standalone export, incremental sync deltas — with EXACTLY
    the file set and array schema of a replicated fp32 control run (masters
    fold into dense_params; `__dense_ef__`/`__dense_master__` never leak to
    disk), and its checkpoint reloads into a fresh dense_wire trainer which
    keeps training."""
    l_q = _run_training(tmp_path, "q", dense_shard=True, dense_wire=fmt)
    _run_training(tmp_path, "c", dense_shard=False)
    assert np.all(np.isfinite(l_q))

    def listing(root):
        out = {}
        for r, _dirs, files in os.walk(root):
            for fn in files:
                p = os.path.join(r, fn)
                out[os.path.relpath(p, root)] = p
        return out

    q, c = listing(tmp_path / "q"), listing(tmp_path / "c")
    assert sorted(q) == sorted(c)
    checked = 0
    for rel, p in q.items():
        if not rel.endswith(".npz"):
            continue
        a, b = np.load(p), np.load(c[rel])
        assert sorted(a.files) == sorted(b.files), rel
        for k in a.files:
            assert "__dense_ef__" not in k and "__dense_master__" not in k, k
            assert a[k].shape == b[k].shape and a[k].dtype == b[k].dtype, \
                (rel, k)
        checked += 1
    assert checked > 0

    tr = MeshTrainer(_model(), embed.Adam(learning_rate=0.01),
                     mesh=make_mesh(), wire="fp32", dense_shard=True,
                     dense_wire=fmt)
    batches = _batches(2, seed=7)
    st = tr.init(batches[0])
    st = tr.load(st, str(tmp_path / "q" / "ckpt"))
    flat = st.dense_slots[zero.ZERO_KEY]
    assert zero.DENSE_MASTER_KEY in flat and zero.DENSE_EF_KEY in flat
    step = tr.jit_train_step(batches[0], st)
    st, m = step(st, batches[1])
    assert np.isfinite(float(m["loss"]))


def test_dense_wire_validation():
    """Config errors fail at construction: dense_wire needs dense_shard,
    unknown formats are rejected, and "fp32"/"none" mean OFF."""
    with pytest.raises(ValueError, match="dense_shard"):
        MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                    mesh=make_mesh(), dense_wire="int8")
    with pytest.raises(ValueError, match="dense_wire"):
        MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                    mesh=make_mesh(), dense_shard=True, dense_wire="int4")
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), dense_shard=True, dense_wire="fp32")
    assert tr.dense_wire is None
    # dense_topk only sizes the sparse_topk payload, and must be positive
    with pytest.raises(ValueError, match="dense_topk"):
        MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                    mesh=make_mesh(), dense_shard=True, dense_wire="int8",
                    dense_topk=32)
    with pytest.raises(ValueError, match="dense_topk"):
        MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                    mesh=make_mesh(), dense_shard=True,
                    dense_wire="sparse_topk", dense_topk=0)
    # set_dense_wire re-validates (it raises before touching the state)
    tr2 = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                      mesh=make_mesh(), dense_shard=True, dense_wire="int8")
    with pytest.raises(ValueError, match="dense_topk"):
        tr2.set_dense_wire(None, "int8", dense_topk=4)
    with pytest.raises(ValueError, match="dense_wire"):
        tr2.set_dense_wire(None, "int4")


# ---------------------------------------------------------------------------
# round 23: stream-sparse dense wire (sparse_topk) units
# ---------------------------------------------------------------------------


def test_sparse_topk_codec_round_trip():
    """pack_topk/unpack_topk: per row the k largest-|x| elements survive
    within int8 in-band quantization error, every untransmitted element
    decodes to EXACT 0.0 (the receiver scatter-sums partials, so stray
    nonzeros would corrupt other sources' contributions), and the index
    lanes are collision-free (<= k nonzeros per row). k=8/40 exercise
    partial codec blocks, k=96 the k == m degenerate case."""
    from openembedding_tpu.ops import wire as wire_mod

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 96)), jnp.float32)
    xn = np.asarray(x)
    for k in (8, 32, 40, 96):
        w = wire_mod.pack_topk(x, k)
        assert w.shape == (4, wire_mod.topk_wire_width(k))
        assert w.dtype == jnp.int8
        out = np.asarray(wire_mod.unpack_topk(w, k, x.shape[-1]))
        for r in range(x.shape[0]):
            idx = np.argsort(-np.abs(xn[r]))[:k]
            mask = np.zeros(x.shape[-1], bool)
            mask[idx] = True
            assert not out[r][~mask].any(), (k, r)
            assert (out[r] != 0).sum() <= k
            np.testing.assert_allclose(
                out[r][mask], xn[r][mask],
                atol=np.abs(xn).max() / 127 + 1e-7, err_msg=f"k={k} row={r}")


def test_sparse_topk_wire_width_partial_blocks():
    """topk_wire_width = int8 in-band rows (value lanes + scales, padded to
    whole codec blocks) + 4 bitcast-int32 index lanes per element; partial
    blocks price a whole block of value lanes, the index lanes are exact."""
    from openembedding_tpu.ops import wire as wire_mod

    for k in (1, 8, 32, 40, 96):
        want = wire_mod.rows_wire_width(k, "int8") + 4 * k
        assert wire_mod.topk_wire_width(k) == want, k
    assert wire_mod.topk_wire_width(32) == 164


def test_sparse_topk_error_feedback_converges():
    """Error feedback at fixed k < chunk: feeding the residual (true value
    minus decoded transmission, which also captures int8 quantization
    error) back into the next encode makes the TIME-AVERAGE of decoded
    transmissions converge to the true per-step gradient at ~1/T — the
    untransmitted mass is delayed, never lost (arXiv:1905.04035)."""
    S_, chunk, k = 4, 32, 8
    rng = np.random.default_rng(2)
    g = jnp.asarray(rng.standard_normal(S_ * chunk), jnp.float32)
    gn = np.asarray(g, np.float64)
    resid = jnp.zeros_like(g)
    sent = np.zeros(S_ * chunk, np.float64)
    errs = {}
    for t in range(1, 51):
        x = g + resid
        enc = zero.encode_flat_topk(x, S_, k)
        dec = zero.decode_flat_topk(enc, k, chunk).reshape(-1)
        resid = x - dec
        sent += np.asarray(dec, np.float64)
        if t in (5, 50):
            errs[t] = np.abs(sent / t - gn).max()
    # telescoping: sent/T - g == -resid_T/T exactly, so convergence only
    # needs the residual to stay bounded — pin both
    assert np.abs(np.asarray(resid)).max() < 2 * np.abs(gn).max()
    assert errs[50] < errs[5] / 4
    assert errs[50] < 0.1


def test_sparse_topk_dense_wire_cost():
    """dense_wire_cost prices sparse honestly: no reduce_scatter, a2a = S
    payloads of topk_wire_width(k) int8 lanes, params all_gather unchanged
    on the 2-byte carrier — and requires the resolved k."""
    from openembedding_tpu.ops import wire as wire_mod

    params = {"w": jnp.zeros((40,), jnp.float32)}
    plan = zero.build_plan(params, embed.Adagrad(learning_rate=0.1), S)
    cost = zero.dense_wire_cost(plan, "sparse_topk", topk=32)
    assert cost["format"] == "sparse_topk" and cost["k"] == 32
    assert cost["rs_bytes"] == 0
    assert cost["a2a_bytes"] == S * wire_mod.topk_wire_width(32)
    assert cost["ag_bytes"] == plan.padded * 2
    assert cost["bytes_per_step"] == cost["a2a_bytes"] + cost["ag_bytes"]
    with pytest.raises(ValueError, match="topk"):
        zero.dense_wire_cost(plan, "sparse_topk")
