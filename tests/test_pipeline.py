"""Software-pipelined train_many (round 18): the dependency-graph overlap
must be FREE in fp32 — bit-exact losses, weights, and optimizer slots vs the
serial scan on every exchange path — and structurally real: batch t+1's
id-plane collectives carry no data dependency on batch t's apply (the jaxpr
pin), the conflict patch repairs deliberately overlapping batches, and the
whole program survives a placement-controller cycle without re-tracing or
changing its collective sequence.

The host-offload stage ring (`offload_stage_depth > 1`) rides along: staging
D batches ahead must stay bit-identical to the synchronous path, with the
per-slot occupancy gauges published.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flax.linen as nn

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.initializers import Constant
from openembedding_tpu.model import EmbeddingModel, Trainer
from openembedding_tpu.models import make_deepfm, make_lr
from openembedding_tpu.parallel import MeshTrainer, make_mesh
from openembedding_tpu.utils import metrics
from openembedding_tpu.utils.guards import (assert_no_recompile,
                                            collective_fingerprint)

VOCAB = 1 << 10
K = 3


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics._REGISTRY.clear()
    yield
    metrics._REGISTRY.clear()


def _stack(batches):
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)


def _run_pair(hot=0, mig=0, group=True, k=K, seed=5, overlap=False,
              wire="fp32"):
    """Train the same window serial and pipelined; return both (state,
    metrics) pairs. `overlap` plants heavy id overlap between consecutive
    batches so the speculative prefetch is guaranteed stale (the conflict
    patch must repair it). `wire` selects the exchange codec — narrow wires
    exercise the round-23 error-feedback replay in the patch."""
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=k, seed=seed))
    if overlap:
        for b in batches[1:]:
            for f in b["sparse"]:
                b["sparse"][f][:8] = batches[0]["sparse"][f][:8]
    stacked = _stack(batches)
    hot_ids = {"categorical": np.arange(4, dtype=np.int64)} if hot else None

    outs = []
    for pipe in (False, True):
        tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=1,
                         hot_rows=hot, mig_rows=mig, group_exchange=group,
                         wire=wire, pipeline_steps=pipe)
        state = tr.init(batches[0])
        if hot:
            state = tr.refresh_hot_rows(state, hot_ids=hot_ids)
        if mig:
            moves = {"categorical": (np.array([8, 16, 24], np.int64),
                                     np.array([3, 5, 7], np.int32))}
            state = tr.migrate_rows(state, moves=moves)
        state, m = tr.jit_train_many(stacked, state)(state, stacked)
        outs.append((tr, state, m))
    return outs


def _assert_bit_exact(sa, ma, sb, mb):
    np.testing.assert_array_equal(np.asarray(ma["loss"]),
                                  np.asarray(mb["loss"]))
    for n in sa.tables:
        np.testing.assert_array_equal(np.asarray(sa.tables[n].weights),
                                      np.asarray(sb.tables[n].weights))
        for s in sa.tables[n].slots:
            np.testing.assert_array_equal(np.asarray(sa.tables[n].slots[s]),
                                          np.asarray(sb.tables[n].slots[s]))
        if sa.tables[n].hot is not None:
            np.testing.assert_array_equal(
                np.asarray(sa.tables[n].hot.weights),
                np.asarray(sb.tables[n].hot.weights))
        if sa.tables[n].mig is not None:
            np.testing.assert_array_equal(
                np.asarray(sa.tables[n].mig.weights),
                np.asarray(sb.tables[n].mig.weights))
        # narrow wires: the per-row error-feedback residuals must match
        # too — the patch's EF replay rewrites them, not just the weights
        if sa.tables[n].ef is not None:
            np.testing.assert_array_equal(np.asarray(sa.tables[n].ef),
                                          np.asarray(sb.tables[n].ef))


# ---------------------------------------------------------------------------
# bit-exactness: every exchange path, pipelined == serial
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("case", ["fused", "hot", "mig", "per_table"])
def test_pipelined_bit_exact(case):
    kw = {"fused": {}, "hot": {"hot": 8}, "mig": {"mig": 8},
          "per_table": {"group": False}}[case]
    (_, sa, ma), (_, sb, mb) = _run_pair(**kw)
    _assert_bit_exact(sa, ma, sb, mb)


def test_pipelined_k1_skips_the_scan():
    """A one-batch window has nothing to overlap — the pipelined path must
    degenerate to the serial result with zero conflict repairs."""
    (_, sa, ma), (_, sb, mb) = _run_pair(k=1)
    _assert_bit_exact(sa, ma, sb, mb)
    assert sum(int(np.asarray(v)) for v in mb["conflict"].values()) == 0


def test_conflict_patch_repairs_overlapping_batches():
    """Consecutive batches share ids, so batch t+1's speculative gather is
    stale the moment batch t applies — the patch must both FIRE (nonzero
    repaired rows, published to the gauge) and restore bit-exactness."""
    (_, sa, ma), (tr, sb, mb) = _run_pair(hot=8, mig=8, overlap=True)
    _assert_bit_exact(sa, ma, sb, mb)
    patched = sum(int(np.asarray(v)) for v in mb["conflict"].values())
    assert patched > 0
    assert int(np.asarray(mb["conflict_overflow"])) == 0
    tr.record_window_stats(mb)
    rep = metrics.report()
    assert rep['exchange.conflict_rows{table="categorical"}'] > 0


@pytest.mark.parametrize("case", ["disjoint", "overlap", "overlap_hot_mig"])
def test_pipelined_bit_exact_int8_wire(case):
    """Round 23's EF replay pin. With the int8 exchange wire every served
    row ships q(w + ef) and rewrites the residual — so a speculatively
    prefetched row is stale in BOTH planes. The conflict patch must replay
    the quantizer against the post-apply weights plus the PRE-serve
    residual stash (`ExchangePlan.ef_stash`), restoring bit-exactness of
    losses, weights, optimizer slots AND the `state.ef` residuals vs the
    serial int8 scan. Overlapping batches force the patch to fire; the
    hot-cache and migration annexes ride the same window."""
    kw = {"disjoint": {}, "overlap": {"overlap": True},
          "overlap_hot_mig": {"overlap": True, "hot": 8, "mig": 8}}[case]
    (_, sa, ma), (_, sb, mb) = _run_pair(wire="int8", **kw)
    _assert_bit_exact(sa, ma, sb, mb)
    for n in sa.tables:
        assert sa.tables[n].ef is not None  # the pin is not vacuous
    patched = sum(int(np.asarray(v)) for v in mb["conflict"].values())
    if case != "disjoint":
        assert patched > 0


# ---------------------------------------------------------------------------
# the jaxpr pin: prefetch is data-independent of the apply
# ---------------------------------------------------------------------------


def _find_scan(jaxpr):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            return eqn
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    found = _find_scan(inner)
                    if found is not None:
                        return found
                elif hasattr(sub, "eqns"):
                    found = _find_scan(sub)
                    if found is not None:
                        return found
    return None


def test_prefetch_has_no_data_dependency_on_apply():
    """THE overlap pin. In the pipelined scan body, batch t+1's exchange
    collectives must be schedulable under batch t's compute — i.e. carry no
    data dependency on anything downstream of batch t's loss. Taint batch
    t's label (every gradient, apply, push, and patch transitively depends
    on it; the id/weight prefetch plane must not) and walk the body jaxpr:
    the first all_to_all is the prefetch and must be clean, while the
    patch/push all_to_alls must be tainted (proving the taint walk itself
    reaches the collectives)."""
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=K, seed=7))
    stacked = _stack(batches)
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=1,
                     wire="fp32", pipeline_steps=True)
    state = tr.init(batches[0])
    many = tr.jit_train_many(stacked, state)

    closed = jax.make_jaxpr(many)(state, stacked)
    scan = _find_scan(closed.jaxpr)
    assert scan is not None, "pipelined train_many lost its scan"
    body = scan.params["jaxpr"].jaxpr
    nc = scan.params["num_consts"]
    nk = scan.params["num_carry"]

    # the scan xs are (head, nxt) slices of the stacked batches — locate
    # batch t's (head's) label leaf to seed the taint
    paths, _ = jax.tree_util.tree_flatten_with_path((stacked, stacked))
    taint_idx = [i for i, (path, _leaf) in enumerate(paths)
                 if path[0] == jax.tree_util.SequenceKey(0)
                 and any(getattr(k, "key", None) == "label" for k in path)]
    assert len(taint_idx) == 1
    x_invars = body.invars[nc + nk:]
    assert len(x_invars) == len(paths)

    tainted = {id(x_invars[taint_idx[0]])}
    for eqn in body.eqns:
        if any(id(v) in tainted for v in eqn.invars):
            tainted.update(id(v) for v in eqn.outvars)

    a2a = [e for e in body.eqns if e.primitive.name == "all_to_all"]
    assert a2a, "no top-level all_to_all in the scan body"
    clean = [e for e in a2a
             if not any(id(v) in tainted for v in e.invars)]
    dirty = [e for e in a2a if e not in clean]
    # the body opens with the prefetch — independent of batch t's loss
    assert a2a[0] in clean
    # id plane + speculative weight return both precede any tainted a2a
    first_dirty = body.eqns.index(dirty[0]) if dirty else len(body.eqns)
    lead = [e for e in clean if body.eqns.index(e) < first_dirty]
    assert len(lead) >= 2, [e.primitive.name for e in a2a]
    # ...and the push/patch plane IS downstream of the loss (the taint
    # walk genuinely reaches collectives; the pin is not vacuous)
    assert dirty, "expected the conflict-patch gather to depend on the apply"


# ---------------------------------------------------------------------------
# placement-controller cycle with pipelining on: no retrace, stable program
# ---------------------------------------------------------------------------

S = 8
POOL = 24
HOT_SHARE = 0.6


class _Tower(nn.Module):
    @nn.compact
    def __call__(self, embedded, dense):
        bias = self.param("bias", nn.initializers.zeros, (1,), jnp.float32)
        return jnp.sum(embedded["a"].astype(jnp.float32), axis=(1, 2)) \
            + bias[0]


def _drift_batches(steps_per_phase, vocab, batch, seed=5):
    """Two-phase drifting-Zipf stream (see tests/test_placement.py): a heavy
    pool homed on shard 5 rotates to shard 3 at half time; the tail cycles
    deterministically so residual imbalance is placement error, not noise."""
    rng = np.random.default_rng(seed)
    pool_a = (np.arange(POOL) * S + 5).astype(np.int64)
    pool_b = (np.arange(POOL) * S + 3).astype(np.int64)
    w = 1.0 / (np.arange(POOL) + 1.0)
    w /= w.sum()
    tail = np.arange(vocab, dtype=np.int64)
    t_off, batches = 0, []
    for i in range(2 * steps_per_phase):
        pool = pool_a if i < steps_per_phase else pool_b
        ids = np.empty((batch, 26), np.int64)
        flat = ids.reshape(-1)
        n = flat.size
        flat[:] = tail[(t_off + np.arange(n)) % vocab]
        t_off += n
        mask = rng.random(n) < HOT_SHARE
        flat[mask] = pool[rng.choice(POOL, size=int(mask.sum()), p=w)]
        batches.append({
            "sparse": {"a": ids.astype(np.int32)},
            "label": rng.integers(0, 2, (batch,)).astype(np.float32)})
    return batches


def test_controller_cycle_keeps_pipelined_program_stable():
    """Prime a controller, let it refresh the hot cache and migrate rows
    across a drift, with the PIPELINED window fn alive the whole time: zero
    re-traces of either fn and an unchanged collective fingerprint — the
    overlap machinery must be as content-swap-invariant as the serial path.
    The controller's per-table adaptive annex sizing (policy.size_mig)
    rides the same cycle: prime installs a dict and publishes the gauge."""
    from openembedding_tpu.placement import (PlacementController,
                                             PlacementPolicy)
    from openembedding_tpu.placement.policy import row_bytes
    from openembedding_tpu.utils.sketch import SkewMonitor

    steps_per_phase = 12
    vocab, batch, dim = 1 << 12, 64, 8
    batches = _drift_batches(steps_per_phase, vocab, batch)
    model = EmbeddingModel(_Tower(), [embed.Embedding(vocab, dim, name="a")])
    mon = SkewMonitor(k=64, sync=True, decay=0.85)
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="fp32", pipeline_steps=True)
    policy = PlacementPolicy(8 * row_bytes(dim, 1), mig_rows=32,
                             refresh_cooldown_steps=3, imbalance_target=1.05)
    ctrl = PlacementController(tr, policy, monitor=mon, interval_steps=3)

    for b in batches[:3]:
        mon.observe("a", b["sparse"]["a"])
    state = tr.init(batches[0])
    state = ctrl.prime(state)
    # satellite pin: prime sized the annex per table and published it
    assert isinstance(tr.mig_rows, dict) and "a" in tr.mig_rows
    assert tr.mig_rows["a"] >= 1
    assert 'placement.mig_rows{table="a"}' in metrics.report()

    window = _stack(batches[:2])
    step = assert_no_recompile(tr.jit_train_step(batches[0], state),
                               label="pipelined_step")
    many = assert_no_recompile(tr.jit_train_many(window, state),
                               label="pipelined_many")
    fp = collective_fingerprint(many, state, window)
    state, _ = many(state, window)  # execute once before the cycle

    for i, b in enumerate(batches):
        mon.observe("a", b["sparse"]["a"])
        state, m = step(state, b)
        metrics.record_step_stats(m["stats"])
        state = ctrl.on_step(state, step=i + 1)
    st = ctrl.status()
    assert st["migrations_applied"] >= 1
    assert st["last_refresh_step"]["a"] > 0

    # the controller refreshed + migrated; the pipelined window must still
    # be the SAME compiled program with the SAME collective sequence
    state, _ = many(state, window)
    assert many.trace_count() == 1
    assert step.trace_count() == 1
    assert collective_fingerprint(many, state, window) == fp


# ---------------------------------------------------------------------------
# host-offload stage ring: depth > 1 staging stays bit-identical
# ---------------------------------------------------------------------------

DIM = 4
CACHE = 4096
ID_SPACE = 1 << 40


def _offload_batches(steps=10, batch=16, seed=11):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        ids = rng.integers(0, ID_SPACE, size=(batch, 2)).astype(np.int64)
        labels = (rng.random(batch) < 0.5).astype(np.float32)
        out.append({"sparse": {"categorical": ids}, "label": labels})
    return out


def _offload_model():
    e = embed.Embedding(-1, DIM, name="categorical", capacity=CACHE,
                        storage="host_cached",
                        embeddings_initializer=Constant(0.0))
    lr = make_lr(vocabulary=-1, hashed=True, capacity=CACHE)
    return EmbeddingModel(lr.module, [e], loss_fn=lr.loss_fn,
                          config=lr.config)


def _offload_run(depth, pipeline=True, stage_ahead=None):
    stage_ahead = depth if stage_ahead is None else stage_ahead
    batches = _offload_batches()
    tr = Trainer(_offload_model(), embed.Adagrad(learning_rate=0.3),
                 offload_pipeline=pipeline, offload_stage_depth=depth)
    state = tr.init(batches[0])
    step = tr.jit_train_step()
    losses = []
    if pipeline:
        for d in range(min(stage_ahead, len(batches))):
            tr.offload_stage(batches[d])
    for i, b in enumerate(batches):
        state = tr.offload_prepare(state, b)
        j = i + stage_ahead
        if pipeline and j < len(batches):
            tr.offload_stage(batches[j])
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    return losses, tr.offload["categorical"]


def test_stage_ring_bit_identical_across_depths():
    """Staging 1, 2, or 3 batches ahead (and under-filling a deep ring)
    must train bit-identically to the synchronous path — a stale staged
    payload falls back, never corrupts."""
    base, _ = _offload_run(1, pipeline=False)
    for depth, ahead in ((1, None), (2, None), (3, None), (2, 1)):
        losses, _ = _offload_run(depth, stage_ahead=ahead)
        np.testing.assert_array_equal(base, losses)


def test_stage_ring_deep_hits_and_occupancy_gauges():
    """With a roomy cache (no eviction churn) a depth-2 ring should serve
    staged payloads, not fall back — and publish per-slot occupancy."""
    _, ot = _offload_run(2)
    assert ot._pipe_hits > 0
    assert set(ot._slot_hits) | set(ot._slot_misses) <= {0, 1}
    rep = metrics.report()
    assert "offload.pipeline_occupancy" in rep
    slot_keys = [k for k in rep
                 if k.startswith('offload.pipeline_occupancy{slot=')]
    assert slot_keys, sorted(rep)


def test_stage_ring_rejects_bad_depth():
    tr = Trainer(_offload_model(), embed.Adagrad(learning_rate=0.3),
                 offload_pipeline=True, offload_stage_depth=0)
    with pytest.raises(ValueError, match="stage_depth"):
        tr.init(_offload_batches(steps=1)[0])


# ---------------------------------------------------------------------------
# per-table adaptive annex sizing (policy.size_mig) unit pins
# ---------------------------------------------------------------------------


def test_size_mig_adapts_to_measured_imbalance():
    from openembedding_tpu.placement.policy import (PlacementPolicy,
                                                    TableTelemetry)
    pol = PlacementPolicy(1 << 20, mig_rows=64, imbalance_target=1.05)
    cov = [(8, 0.5)]
    load = np.array([100.0] * 7 + [200.0])   # shard 7 runs hot
    hot_homed = [(7 + 8 * k, 100) for k in range(20)]  # ids with id%8==7

    tels = [
        # no measured load vector yet -> static default
        TableTelemetry("cold", 4, cov, total=9000.0, top_ids=hot_homed),
        # balanced -> floor
        TableTelemetry("flat", 4, cov, total=9000.0, top_ids=hot_homed,
                       shard_positions=np.full(8, 100.0)),
        # skewed, sketch covers the excess -> sized between the clamps
        TableTelemetry("skew", 4, cov, total=9000.0, top_ids=hot_homed,
                       shard_positions=load),
        # skewed but tracked mass can't cover the excess -> cap
        TableTelemetry("deep", 4, cov, total=9000.0, top_ids=[(7, 10)],
                       shard_positions=load),
    ]
    sized = pol.size_mig(tels)
    assert sized["cold"] == 64
    assert sized["flat"] == 16           # mig_rows // 4
    # excess = 200 - 1.05*112.5 = 81.875; each hot-homed id covers
    # 100/9000*900 = 10 -> 9 ids needed -> M = 2*9 = 18
    assert sized["skew"] == 18
    assert sized["deep"] == 256          # 4 * mig_rows
    # off-shard heavy hitters must not count toward coverage
    mixed = TableTelemetry(
        "mixed", 4, cov, total=9000.0,
        top_ids=[(6, 10**6), (5, 10**6)] + hot_homed,  # id%8 != 7: ignored
        shard_positions=load)
    assert pol.size_mig([mixed])["mixed"] == 18


# ---------------------------------------------------------------------------
# round 23: dense-wire policy hysteresis (no thrash under noisy density)
# ---------------------------------------------------------------------------


def test_dense_wire_policy_hysteresis_no_thrash():
    """A density that oscillates inside the hysteresis band [enter, exit)
    must flip the wire exactly once: enter sparse when d <= enter
    (0.6 x crossover), stay sparse until d >= exit (0.9 x crossover) —
    each flip is a counted re-jit, so thrash here is a compile storm."""
    from openembedding_tpu.placement.policy import PlacementPolicy

    pol = PlacementPolicy(1 << 20, mig_rows=64)
    chunk = 1024
    enter = pol.dense_sparse_enter * pol.dense_wire_crossover
    exit_ = pol.dense_sparse_exit * pol.dense_wire_crossover
    assert enter < exit_ < pol.dense_wire_crossover

    mode, flips = "int8", 0
    # every sample sits strictly between enter and exit except the first,
    # which trips the entry — the band must absorb all the oscillation
    stream = [0.10] + [enter + 0.01, exit_ - 0.01, enter + 0.005,
                       exit_ - 0.002] * 4
    for d in stream:
        new, k, _reason = pol.recommend_dense_wire(d, current=mode,
                                                   chunk=chunk,
                                                   steps_since=10**9)
        if new != mode:
            flips += 1
        mode = new
        if mode == "sparse_topk":
            assert 1 <= k <= chunk and k % pol.dense_topk_block == 0
    assert flips == 1 and mode == "sparse_topk"

    # leaving the band upward flips back out...
    new, k, _ = pol.recommend_dense_wire(exit_ + 0.01, current=mode,
                                         chunk=chunk, steps_since=10**9)
    assert new == "int8" and k is None
    # ...but never inside the cooldown window
    new, _k, reason = pol.recommend_dense_wire(
        0.01, current="int8", chunk=chunk,
        steps_since=pol.dense_wire_cooldown_steps - 1)
    assert new == "int8" and "cooldown" in reason
    # unusable densities never recommend a change
    for bad in (float("nan"), -1.0):
        new, k, _ = pol.recommend_dense_wire(bad, current="int8",
                                             chunk=chunk, steps_since=10**9)
        assert new == "int8" and k is None
