"""SLO engine tests (`utils/slo.py`): spec parsing/validation, the
never-observed → UNKNOWN trap, burn-rate windows (including a fast window
shorter than one evaluation interval), gauge-vs-hist selector behavior,
worst-series judging under labels=None, breach/recovery transitions with
their flight-recorder events, exit-code semantics, and the raising-sink
survival rule the PeriodicReporter pinned in round 9."""

import json
import time

import pytest

from openembedding_tpu.utils import metrics, slo, trace


@pytest.fixture(autouse=True)
def _fresh():
    metrics._REGISTRY.clear()
    trace.RECORDER.clear()
    yield
    metrics._REGISTRY.clear()
    trace.RECORDER.clear()


# -- spec parsing + validation ------------------------------------------------


def test_spec_validation_rejects_bad_fields():
    with pytest.raises(ValueError, match="selector"):
        slo.SLOSpec(name="s", metric="g.m", threshold=1.0, selector="p33")
    with pytest.raises(ValueError, match="op"):
        slo.SLOSpec(name="s", metric="g.m", threshold=1.0, op="~=")
    with pytest.raises(ValueError, match="slow window"):
        slo.SLOSpec(name="s", metric="g.m", threshold=1.0,
                    fast_window_s=60.0, slow_window_s=10.0)
    with pytest.raises(ValueError, match="unknown"):
        slo.parse_spec({"name": "s", "metric": "g.m", "threshold": 1.0,
                        "tresholdd": 2.0})


def test_load_specs_checked_in_file(tmp_path):
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    specs = slo.load_specs(os.path.join(repo, "tools", "slo_specs.json"))
    assert {s.name for s in specs} >= {"predict_p99", "numerics",
                                       "sync_freshness"}
    # non-list file is rejected
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"name": "x"}))
    with pytest.raises(ValueError, match="list"):
        slo.load_specs(str(bad))


# -- the UNKNOWN trap ---------------------------------------------------------


def test_never_observed_metric_is_unknown_not_ok():
    ev = slo.SLOEvaluator([slo.SLOSpec(name="lag",
                                       metric="sync.version_lag_steps",
                                       threshold=50.0)])
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.UNKNOWN
    assert v["value"] is None
    # absence of evidence is not a pass: the exit gate stays non-zero
    assert ev.exit_code() == 2
    # ...and a snapshot-less evaluator is also non-zero
    assert slo.SLOEvaluator([]).exit_code() == 2

    metrics.observe("sync.version_lag_steps", 3.0, "gauge")
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.OK and v["value"] == 3.0
    assert ev.exit_code() == 0


def test_resetting_reporter_wipes_counter_evidence_back_to_unknown():
    """The documented trap: `report(reset=True)` zeroes a counter's window,
    so the SLO sees never-observed again — judgment-bearing nodes must
    report with reset=False (as tools/sync_soak.py does)."""
    spec = slo.SLOSpec(name="numerics", metric="health.nonfinite_total",
                       threshold=0.0, op="==")
    ev = slo.SLOEvaluator([spec])
    metrics.observe("health.nonfinite_total", 0.0)
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.OK
    metrics.report(reset=True)
    ev2 = slo.SLOEvaluator([spec])  # fresh history: only the registry counts
    (v,) = ev2.evaluate_now()
    assert v["verdict"] == slo.UNKNOWN
    # the non-resetting report keeps the evidence
    metrics.observe("health.nonfinite_total", 0.0)
    metrics.report(reset=False)
    (v,) = ev2.evaluate_now()
    assert v["verdict"] == slo.OK


def test_peek_never_creates_the_metric():
    ev = slo.SLOEvaluator([slo.SLOSpec(name="lag", metric="sync.never_seen",
                                       threshold=1.0)])
    ev.evaluate_now()
    with metrics._LOCK:
        names = {a.name for a in metrics._REGISTRY.values()}
    assert "sync.never_seen" not in names


# -- burn-rate windows --------------------------------------------------------


def test_fast_window_shorter_than_interval_judges_latest_sample():
    """fast_window_s=0 with a tiny burn threshold = trip on the FIRST bad
    sample (the numerics SLO shape): the latest sample is always in scope
    even when the window is shorter than one evaluation interval."""
    spec = slo.SLOSpec(name="numerics", metric="health.nonfinite_total",
                       threshold=0.0, op="==", fast_window_s=0.0,
                       slow_window_s=300.0, burn_threshold=1e-9)
    ev = slo.SLOEvaluator([spec])
    t0 = 1000.0
    metrics.observe("health.nonfinite_total", 0.0, "gauge")
    (v,) = ev.evaluate_now(now=t0)
    assert v["verdict"] == slo.OK
    metrics.observe("health.nonfinite_total", 5.0, "gauge")
    (v,) = ev.evaluate_now(now=t0 + 10)
    assert v["verdict"] == slo.BREACHED
    assert v["value"] == 5.0
    # recovery is symmetric: a clean latest sample clears the fast window
    # (BREACHED needs BOTH windows burning), while the slow window still
    # remembers the bad sample — the breach survives in the flight recorder
    # and the slo.breaches counter, not in the live verdict
    metrics.observe("health.nonfinite_total", 0.0, "gauge")
    (v,) = ev.evaluate_now(now=t0 + 20)
    assert v["verdict"] == slo.OK
    assert v["slow_bad_frac"] == pytest.approx(1 / 3)
    assert metrics.Accumulator.get("slo.breaches").value() == 1


def test_single_blip_does_not_breach_multiwindow():
    """Default burn shape (0.5 in both windows): one bad sample among good
    ones inside the fast window does not page."""
    spec = slo.SLOSpec(name="p99", metric="serving.predict.ms",
                       selector="p99", threshold=100.0,
                       fast_window_s=60.0, slow_window_s=300.0,
                       burn_threshold=0.5)
    ev = slo.SLOEvaluator([spec])
    t0 = 2000.0
    for i in range(4):
        metrics.observe("serving.predict.ms", 5.0, "hist")
        ev.evaluate_now(now=t0 + i)
    # a tail blip: p99 now fails, but it is 1 bad among 5 fast samples
    for _ in range(200):
        metrics.observe("serving.predict.ms", 500.0, "hist")
    (v,) = ev.evaluate_now(now=t0 + 4)
    assert v["verdict"] == slo.OK
    assert v["fast_bad_frac"] == pytest.approx(0.2)
    # sustained burn: bad fraction crosses 0.5 in both windows
    verdicts = [ev.evaluate_now(now=t0 + 5 + i)[0] for i in range(8)]
    assert verdicts[-1]["verdict"] == slo.BREACHED


# -- selector semantics -------------------------------------------------------


def test_hist_selector_on_gauge_reads_the_scalar():
    """A spec written for a histogram still evaluates if the metric turns
    out to be a gauge: every selector degrades to value()."""
    metrics.observe("exchange.cost_drift", 0.25, "gauge")
    ev = slo.SLOEvaluator([slo.SLOSpec(name="drift",
                                       metric="exchange.cost_drift",
                                       selector="p99", threshold=2.0)])
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.OK
    assert v["value"] == pytest.approx(0.25)


def test_hist_quantile_selector_judges_the_quantile():
    for ms in (1.0,) * 98 + (900.0,) * 2:
        metrics.observe("serving.predict.ms", ms, "hist")
    make = lambda sel, thr: slo.SLOEvaluator(  # noqa: E731
        [slo.SLOSpec(name="s", metric="serving.predict.ms",
                     selector=sel, threshold=thr, fast_window_s=0.0,
                     burn_threshold=1e-9)])
    (v,) = make("p50", 10.0).evaluate_now()
    assert v["verdict"] == slo.OK
    (v,) = make("p99", 10.0).evaluate_now()
    assert v["verdict"] == slo.BREACHED
    assert v["value"] > 10.0


def test_labels_none_judges_worst_series():
    """labels=None matches every label set; ONE failing table fails the
    per-table objective."""
    metrics.observe("health.grad_norm", 1.0, "gauge", labels={"table": "a"})
    metrics.observe("health.grad_norm", 50.0, "gauge", labels={"table": "b"})
    ev = slo.SLOEvaluator([slo.SLOSpec(name="gn", metric="health.grad_norm",
                                       threshold=10.0, fast_window_s=0.0,
                                       burn_threshold=1e-9)])
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.BREACHED and v["value"] == 50.0
    # pinning the labels to the healthy series passes
    ev2 = slo.SLOEvaluator([slo.SLOSpec(name="gn", metric="health.grad_norm",
                                        labels={"table": "a"},
                                        threshold=10.0)])
    (v,) = ev2.evaluate_now()
    assert v["verdict"] == slo.OK and v["value"] == 1.0


# -- transitions, metrics, events, exit codes ---------------------------------


def test_breach_transition_emits_event_counter_and_recovers():
    spec = slo.SLOSpec(name="lag", metric="sync.version_lag_steps",
                       threshold=10.0, fast_window_s=0.0,
                       slow_window_s=10.0, burn_threshold=1e-9)
    ev = slo.SLOEvaluator([spec])
    t0 = 3000.0
    metrics.observe("sync.version_lag_steps", 99.0, "gauge")
    (v,) = ev.evaluate_now(now=t0)
    assert v["verdict"] == slo.BREACHED
    assert ev.exit_code() == 1
    assert metrics.Accumulator.get("slo.breaches").value() == 1
    assert metrics.Accumulator.get(
        "slo.ok", "gauge", labels={"slo": "lag"}).value() == 0.0
    breaches = [e for e in trace.RECORDER.tail() if e.name == "breach"]
    assert len(breaches) == 1 and breaches[0].attrs["slo"] == "lag"
    # still breached next round: no second breach event (transition-edge only)
    ev.evaluate_now(now=t0 + 1)
    assert metrics.Accumulator.get("slo.breaches").value() == 1
    assert len([e for e in trace.RECORDER.tail()
                if e.name == "breach"]) == 1
    # recovery: lag drops, bad samples age out of the 10s slow window
    metrics.observe("sync.version_lag_steps", 2.0, "gauge")
    (v,) = ev.evaluate_now(now=t0 + 20)
    assert v["verdict"] == slo.OK
    assert any(e.name == "recovered" for e in trace.RECORDER.tail())
    assert metrics.Accumulator.get(
        "slo.ok", "gauge", labels={"slo": "lag"}).value() == 1.0
    assert ev.exit_code() == 0


def test_exit_code_breached_beats_unknown():
    metrics.observe("sync.version_lag_steps", 99.0, "gauge")
    ev = slo.SLOEvaluator([
        slo.SLOSpec(name="lag", metric="sync.version_lag_steps",
                    threshold=10.0, fast_window_s=0.0, burn_threshold=1e-9),
        slo.SLOSpec(name="ghost", metric="serving.predict.ms",
                    threshold=10.0),
    ])
    ev.evaluate_now()
    assert ev.exit_code() == 1  # BREACHED outranks the UNKNOWN spec


def test_render_text_and_snapshot_shapes():
    ev = slo.SLOEvaluator([slo.SLOSpec(name="lag",
                                       metric="sync.version_lag_steps",
                                       threshold=10.0)])
    assert ev.render_text() == "(no SLO verdicts yet)"
    ev.evaluate_now()
    text = ev.render_text()
    assert "UNKNOWN" in text and "never-observed" in text
    (snap,) = ev.snapshot()
    assert snap["name"] == "lag" and snap["verdict"] == slo.UNKNOWN


# -- background evaluator survives a raising sink -----------------------------


def test_background_evaluator_survives_raising_sink():
    calls = []

    def bad_sink(verdicts):
        calls.append(len(verdicts))
        raise RuntimeError("sink died")

    metrics.observe("sync.version_lag_steps", 1.0, "gauge")
    ev = slo.SLOEvaluator([slo.SLOSpec(name="lag",
                                       metric="sync.version_lag_steps",
                                       threshold=10.0)],
                          interval_s=0.02, sink=bad_sink)
    with ev:
        deadline = time.time() + 5.0
        while len(calls) < 3 and time.time() < deadline:
            time.sleep(0.01)
    assert len(calls) >= 3  # kept evaluating after every raise
    assert metrics.Accumulator.get("slo.eval_errors").value() >= 3
    # the verdicts themselves stayed healthy
    assert ev.exit_code() == 0


def test_configure_replaces_specs_and_drops_stale_history():
    ev = slo.SLOEvaluator([slo.SLOSpec(name="old", metric="sync.rollbacks",
                                       threshold=0.0)])
    ev.evaluate_now()
    assert [v["name"] for v in ev.snapshot()] == ["old"]
    ev.configure([slo.SLOSpec(name="new", metric="sync.rollbacks",
                              threshold=0.0)])
    assert ev.snapshot() == []  # old verdict history discarded
    ev.evaluate_now()
    assert [v["name"] for v in ev.snapshot()] == ["new"]
