"""Native (no-TensorFlow) TFRecord reader vs the tf.data path: bit parity.

The reference's benchmark feeds TFRecord (`test/benchmark/criteo_tfrecord.py`,
readers in `test/benchmark/criteo_deepctr.py:168-240`); the native reader
(`native/oetpu_data.cpp::TfrReader`) parses the same files — CRC-verified
framing, hand-rolled proto-wire Example parser — with zero TF dependency.
TF is only used HERE, to write the fixture files and as the parity oracle."""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from openembedding_tpu.data.criteo import (NUM_DENSE, NUM_SPARSE,
                                           read_criteo_tfrecord)
from openembedding_tpu.native import NativeCriteoTFRecordReader, available

pytestmark = pytest.mark.skipif(not available(),
                                reason="native toolchain unavailable")


def _write_tfrecord(path, rows, seed):
    """The reference's schema: label int64[1], I1..13 float[1], C1..26
    int64[1] (`test/benchmark/criteo_tfrecord.py`)."""
    rng = np.random.default_rng(seed)
    records = []
    with tf.io.TFRecordWriter(str(path)) as w:
        for _ in range(rows):
            label = int(rng.integers(0, 2))
            dense = rng.standard_normal(NUM_DENSE).astype(np.float32)
            cats = rng.integers(0, 1 << 20, NUM_SPARSE)
            feat = {"label": tf.train.Feature(
                int64_list=tf.train.Int64List(value=[label]))}
            for i in range(NUM_DENSE):
                feat[f"I{i + 1}"] = tf.train.Feature(
                    float_list=tf.train.FloatList(value=[float(dense[i])]))
            for i in range(NUM_SPARSE):
                feat[f"C{i + 1}"] = tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[int(cats[i])]))
            ex = tf.train.Example(
                features=tf.train.Features(feature=feat))
            w.write(ex.SerializeToString())
            records.append((label, dense, cats))
    return records


def _collect(it):
    out = []
    for b in it:
        out.append((b["label"].copy(),
                    np.asarray(b["dense"]).copy(),
                    np.asarray(b["sparse"]["categorical"]).copy()))
    return out


def test_native_matches_tf_single_file(tmp_path):
    p = tmp_path / "a.tfrecord"
    _write_tfrecord(p, 100, seed=0)
    kw = dict(batch_size=32, id_space=1 << 22, drop_remainder=False)
    want = _collect(read_criteo_tfrecord([str(p)], **kw))
    got = _collect(read_criteo_tfrecord([str(p)], engine="native", **kw))
    assert len(got) == len(want) == 4  # 3 full + remainder 4
    for (gl, gd, gs), (wl, wd, ws) in zip(got, want):
        np.testing.assert_array_equal(gl, wl)
        np.testing.assert_array_equal(gd, wd)  # same f32 bits end to end
        np.testing.assert_array_equal(gs, ws)


def test_native_matches_tf_multi_file_and_fold_offsets(tmp_path):
    """Multi-file order matches the tf path's pinned deterministic
    file-sequential order, and the vocab_sizes offset-folding path matches
    too."""
    pa, pb = tmp_path / "a.tfrecord", tmp_path / "b.tfrecord"
    _write_tfrecord(pa, 40, seed=1)
    _write_tfrecord(pb, 40, seed=2)
    vocab_sizes = [1 << 20] * NUM_SPARSE
    kw = dict(batch_size=16, vocab_sizes=vocab_sizes, drop_remainder=True)
    want = _collect(read_criteo_tfrecord([str(pa), str(pb)], **kw))
    got = _collect(read_criteo_tfrecord([str(pa), str(pb)], engine="native",
                                        **kw))
    assert len(got) == len(want) == 5
    for (gl, gd, gs), (wl, wd, ws) in zip(got, want):
        np.testing.assert_array_equal(gl, wl)
        np.testing.assert_array_equal(gd, wd)
        np.testing.assert_array_equal(gs, ws)


def test_native_host_sharding_partitions(tmp_path):
    """Record-level host sharding: the two hosts' shards are disjoint and
    their union is the whole file."""
    p = tmp_path / "a.tfrecord"
    _write_tfrecord(p, 60, seed=3)

    def rows_of(host_id, num_hosts):
        out = []
        for b in NativeCriteoTFRecordReader(
                [str(p)], 8, host_id=host_id, num_hosts=num_hosts,
                drop_remainder=False):
            out.extend(np.asarray(b["sparse"]["categorical"])[:, 0].tolist())
        return out

    h0, h1 = rows_of(0, 2), rows_of(1, 2)
    every = rows_of(0, 1)
    assert len(h0) == len(h1) == 30
    assert sorted(h0 + h1) == sorted(every)
    assert not (set(h0) & set(h1))


def test_native_rejects_corrupt_frame(tmp_path):
    p = tmp_path / "a.tfrecord"
    _write_tfrecord(p, 10, seed=4)
    raw = bytearray(p.read_bytes())
    raw[20] ^= 0xFF  # flip a payload byte: data CRC must catch it
    p.write_bytes(bytes(raw))
    with pytest.raises(IOError):
        _collect(NativeCriteoTFRecordReader([str(p)], 8,
                                            drop_remainder=False))


def test_native_rejects_missing_schema_key(tmp_path):
    """STRICT schema like the tf path's FixedLenFeature: a record missing C5
    must fail the read, never train on fabricated zeros."""
    p = tmp_path / "a.tfrecord"
    feat = {"label": tf.train.Feature(
        int64_list=tf.train.Int64List(value=[1]))}
    for i in range(NUM_DENSE):
        feat[f"I{i + 1}"] = tf.train.Feature(
            float_list=tf.train.FloatList(value=[0.5]))
    for i in range(NUM_SPARSE):
        if i == 4:
            continue  # C5 missing
        feat[f"C{i + 1}"] = tf.train.Feature(
            int64_list=tf.train.Int64List(value=[int(i)]))
    with tf.io.TFRecordWriter(str(p)) as w:
        w.write(tf.train.Example(
            features=tf.train.Features(feature=feat)).SerializeToString())
    with pytest.raises(IOError):
        _collect(NativeCriteoTFRecordReader([str(p)], 8,
                                            drop_remainder=False))
