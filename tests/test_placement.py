"""Self-driving placement (round 12): the autonomous controller that sizes
the hot cache, paces refreshes, and re-shards the cold tail
(`openembedding_tpu/placement/`, `MeshTrainer(mig_rows=...)`,
`parallel/sharded.py` "COLD-TAIL RE-SHARDING").

Acceptance (ISSUE 7):
- E2E drift: under Zipf traffic whose hot set rotates mid-run, the
  controller — configured with ONLY a replicated-byte budget — refreshes
  the hot cache and migrates cold rows such that the final
  `exchange.shard_imbalance` is <= 1.15 and the hot hit-ratio lands within
  0.05 of the sketch-predicted coverage, with zero re-compiles across every
  refresh + migration (utils/guards);
- persistence oblivious: checkpoints, exports and incremental-persist
  deltas from a placement-driven run are byte-identical to a placement-off
  control run on the same batches (fp32 wire: training itself is bit-exact
  through migration — the annex apply takes the identical source-major
  reduction path);
- the policy/planner math is unit-pinned: budget flows to the most skewed
  table, refresh hysteresis honors gain threshold + cooldown, the
  migration planner flattens a planted hot spot and never moves hot ids.
"""

import os

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.model import EmbeddingModel
from openembedding_tpu.parallel import MeshTrainer, make_mesh
from openembedding_tpu.placement import (PlacementController,
                                         PlacementPolicy, plan_migration,
                                         render_status)
from openembedding_tpu.placement.policy import TableTelemetry, row_bytes
from openembedding_tpu.utils import metrics
from openembedding_tpu.utils.guards import assert_no_recompile
from openembedding_tpu.utils.sketch import SkewMonitor

S = 8  # conftest forces 8 virtual CPU devices
B = 64
VOCAB = 1 << 12
DIM = 8
POOL = 24          # planted heavy ids, all homed on one shard
HOT_SHARE = 0.6    # share of positions drawn from the heavy pool


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics._REGISTRY.clear()
    yield
    metrics._REGISTRY.clear()


class _Tower(nn.Module):
    @nn.compact
    def __call__(self, embedded, dense):
        bias = self.param("bias", nn.initializers.zeros, (1,), jnp.float32)
        return jnp.sum(embedded["a"].astype(jnp.float32), axis=(1, 2)) \
            + bias[0]


def _model():
    return EmbeddingModel(_Tower(), [embed.Embedding(VOCAB, DIM, name="a")])


def _drift_batches(steps_per_phase, seed=5):
    """Two-phase drifting-Zipf stream: a 1/(r+1)-weighted heavy pool homed
    entirely on shard 5, rotated to a different pool homed on shard 3 at
    half time. The tail cycles DETERMINISTICALLY over the vocab so its
    per-shard load is flat — residual imbalance is pure placement error,
    not sampling noise."""
    rng = np.random.default_rng(seed)
    pool_a = (np.arange(POOL) * S + 5).astype(np.int64)
    pool_b = (np.arange(POOL) * S + 3).astype(np.int64)
    w = 1.0 / (np.arange(POOL) + 1.0)
    w /= w.sum()
    tail = np.arange(VOCAB, dtype=np.int64)
    t_off, batches = 0, []
    for i in range(2 * steps_per_phase):
        pool = pool_a if i < steps_per_phase else pool_b
        ids = np.empty((B, 26), np.int64)
        flat = ids.reshape(-1)
        n = flat.size
        flat[:] = tail[(t_off + np.arange(n)) % VOCAB]
        t_off += n
        mask = rng.random(n) < HOT_SHARE
        flat[mask] = pool[rng.choice(POOL, size=int(mask.sum()), p=w)]
        batches.append({
            "sparse": {"a": ids.astype(np.int32)},
            "label": rng.integers(0, 2, (B,)).astype(np.float32)})
    return batches


# ---------------------------------------------------------------------------
# E2E: the acceptance drift test
# ---------------------------------------------------------------------------


def test_e2e_drift_controller_closes_the_loop():
    """THE acceptance pin: rotate the hot set mid-run; the controller gets
    nothing but a byte budget and must (a) size H, (b) refresh the cache
    after the drift, (c) migrate the heavy-but-not-hot tail — ending with
    shard imbalance <= 1.15 and a hit ratio within 0.05 of the sketch's
    predicted coverage, without ever re-jitting the step."""
    steps_per_phase = 15
    batches = _drift_batches(steps_per_phase)
    mon = SkewMonitor(k=64, sync=True, decay=0.85)
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="fp32")
    budget = 8 * row_bytes(DIM, 1)  # fits exactly 8 hot rows
    policy = PlacementPolicy(budget, mig_rows=32,
                             refresh_cooldown_steps=3,
                             imbalance_target=1.05)
    ctrl = PlacementController(tr, policy, monitor=mon, interval_steps=3)

    for b in batches[:3]:  # warm the sketches so prime() can size
        mon.observe("a", b["sparse"]["a"])
    state = tr.init(batches[0])
    state = ctrl.prime(state)
    assert tr.hot_rows == {"a": 8}, tr.hot_rows       # sized from the budget
    assert state.tables["a"].hot is not None
    assert state.tables["a"].mig is not None
    step = assert_no_recompile(tr.jit_train_step(batches[0], state),
                               label="placement_step")

    tail_stats = []
    for i, b in enumerate(batches):
        mon.observe("a", b["sparse"]["a"])
        state, m = step(state, b)
        tail_stats.append(jax.device_get(m["stats"]))
        tail_stats = tail_stats[-3:]
        metrics.record_step_stats(m["stats"])
        state = ctrl.on_step(state, step=i + 1)
    # zero re-compiles across every refresh + migration the controller made
    assert step.trace_count() == 1
    st = ctrl.status()
    assert st["migrations_applied"] >= 1
    assert st["last_refresh_step"]["a"] > steps_per_phase  # refreshed post-drift

    last = tail_stats[-1]
    # final imbalance over the last three steps (one step's tail sample
    # carries binomial noise; the controller's steady state is the product)
    pos = np.mean([np.asarray(s["a/shard_positions"], np.float64)
                   for s in tail_stats], axis=0)
    final_imbalance = float(pos.max() / pos.mean())
    assert final_imbalance <= 1.15, final_imbalance
    hit = float(last["a/hot_hits"]) / float(last["a/pull_indices"])
    predicted = dict(mon.sketch("a").coverage([8]))[8]
    assert abs(hit - predicted) < 0.05, (hit, predicted)
    assert hit > 0.3
    # the directory actually served re-homed traffic
    assert float(last["a/mig_hits"]) > 0
    # decision telemetry reached the gauges
    rep = metrics.report()
    assert rep["placement.refreshes"] >= 1
    assert rep['placement.moved_ratio{table="a"}'] > 0
    # /statusz panel renders this controller
    txt = render_status()
    assert "hot_rows=8" in txt and "migrations_applied=" in txt
    assert "last_refresh=step" in txt


# ---------------------------------------------------------------------------
# Persistence obliviousness: checkpoints / export / deltas byte-identical
# ---------------------------------------------------------------------------


def _run_training(tmp_path, tag, *, placement):
    from openembedding_tpu.export import export_standalone
    from openembedding_tpu.persist import IncrementalPersister, PersistPolicy
    batches = _drift_batches(6, seed=7)
    mon = SkewMonitor(k=64, sync=True, decay=0.9)
    kw = {}
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="fp32", **kw)
    ctrl = None
    if placement:
        policy = PlacementPolicy(8 * row_bytes(DIM, 1), mig_rows=16,
                                 refresh_cooldown_steps=2,
                                 imbalance_target=1.05)
        ctrl = PlacementController(tr, policy, monitor=mon,
                                   interval_steps=2)
        for b in batches[:2]:
            mon.observe("a", b["sparse"]["a"])
    state = tr.init(batches[0])
    if ctrl is not None:
        state = ctrl.prime(state)
    step = tr.jit_train_step(batches[0], state)
    root = tmp_path / tag
    losses = []
    with IncrementalPersister(tr, tr.model, str(root / "persist"), window=1,
                              policy=PersistPolicy(every_steps=2),
                              full_every=100) as p:
        for i, b in enumerate(batches):
            if ctrl is not None:
                mon.observe("a", b["sparse"]["a"])
            state, m = step(state, b)
            losses.append(float(m["loss"]))
            if ctrl is not None:
                state = ctrl.on_step(state, step=i + 1)
            p.maybe_persist(state, batch=b)
        p.wait()
    tr.save(state, str(root / "ckpt"), model_sign="t")
    synced = tr.hot_sync(state)
    export_standalone(synced, tr.model, str(root / "export"),
                      model_sign="t-0")
    return losses


def _assert_trees_equal(off_root, on_root, skip=("model_meta",)):
    found = 0
    for root, _dirs, files in os.walk(off_root):
        for fn in files:
            if fn in skip:
                continue
            p_off = os.path.join(root, fn)
            p_on = p_off.replace(str(off_root), str(on_root))
            with open(p_off, "rb") as fa, open(p_on, "rb") as fb:
                assert fa.read() == fb.read(), f"differs: {p_off}"
            found += 1
    assert found > 0


def test_checkpoint_export_delta_byte_identical(tmp_path):
    """A placement-driven run's on-disk artifacts — sharded checkpoint,
    standalone export, incremental deltas — equal a placement-off control
    run's byte for byte (the `hot_sync` hook writes hot rows AND migrated
    rows back before every reader), and training losses match exactly."""
    l_off = _run_training(tmp_path, "off", placement=False)
    l_on = _run_training(tmp_path, "on", placement=True)
    assert l_off == l_on
    _assert_trees_equal(tmp_path / "off" / "ckpt", tmp_path / "on" / "ckpt")
    _assert_trees_equal(tmp_path / "off" / "export",
                        tmp_path / "on" / "export",
                        skip=("model_meta", "model_meta.json"))
    # delta payload files (table_*.npz) under the persist root
    import glob
    offs = sorted(glob.glob(str(tmp_path / "off" / "persist" / "**" /
                                "table_*.npz"), recursive=True))
    assert offs
    for p_off in offs:
        p_on = p_off.replace(str(tmp_path / "off"), str(tmp_path / "on"))
        a, b = np.load(p_off), np.load(p_on)
        assert sorted(a.files) == sorted(b.files), p_off
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k],
                                          err_msg=f"{p_off}:{k}")


# ---------------------------------------------------------------------------
# Policy / planner units
# ---------------------------------------------------------------------------


def _curve(shares):
    return list(enumerate(shares, start=1))


def test_policy_budget_flows_to_the_skewed_table():
    """Greedy traffic-per-byte: a heavily skewed table's knee outbids a
    flat table's head, so the skewed table gets (most of) the rows."""
    skewed = TableTelemetry(
        name="skewed", dim=8, total=10000.0,
        coverage=_curve([0.30, 0.45, 0.55, 0.62, 0.66, 0.68, 0.69, 0.70]))
    flat = TableTelemetry(
        name="flat", dim=8, total=10000.0,
        coverage=_curve([0.01, 0.02, 0.03, 0.04, 0.05, 0.06, 0.07, 0.08]))
    policy = PlacementPolicy(6 * row_bytes(8, 1))
    sizes = policy.size_hot([skewed, flat])
    assert sizes["skewed"] == 6 and sizes["flat"] == 0, sizes
    # a bigger budget spills over once the skewed curve flattens below the
    # flat table's (constant) marginal rate
    policy2 = PlacementPolicy(12 * row_bytes(8, 1))
    sizes2 = policy2.size_hot([skewed, flat])
    assert sizes2["skewed"] >= 6 and sum(sizes2.values()) == 12, sizes2
    assert sizes2["flat"] > 0


def test_policy_refresh_hysteresis_gain_and_cooldown():
    t = TableTelemetry(
        name="a", dim=8, total=1000.0,
        coverage=_curve([0.3, 0.5, 0.6, 0.65]),
        top_ids=[(1, 300), (2, 200), (3, 100), (4, 50)])
    policy = PlacementPolicy(1 << 20, refresh_min_gain=0.05,
                             refresh_cooldown_steps=10)
    # inside the cooldown: never, whatever the gain
    due, reason, _ = policy.refresh_due(t, np.asarray([9]), H=2,
                                        steps_since=5)
    assert not due and "cooldown" in reason
    # installed set empty -> initial promotion
    due, reason, _ = policy.refresh_due(t, np.zeros((0,), np.int64), H=2,
                                        steps_since=100)
    assert due and "initial" in reason
    # installed == current top-H: gain ~0, below threshold
    due, reason, gain = policy.refresh_due(t, np.asarray([1, 2]), H=2,
                                           steps_since=100)
    assert not due and gain < 0.05
    # fully rotated installed set: gain = the whole top-H coverage
    due, _reason, gain = policy.refresh_due(t, np.asarray([8, 9]), H=2,
                                            steps_since=100)
    assert due and gain >= 0.49


def test_plan_migration_flattens_planted_hot_spot():
    # shard 5 carries 3x the mean; candidates all homed there
    load = np.asarray([100, 100, 100, 100, 100, 500, 100, 100], np.float64)
    cands = [(5 + 8 * r, 50.0) for r in range(10)]  # id % 8 == 5
    ids, owners, proj = plan_migration(
        load, cands, num_shards=8, max_moves=16, target=1.05,
        total=float(sum(w for _i, w in cands) / 0.33))
    assert ids.size >= 6
    assert all(o != 5 for o in owners.tolist())
    assert proj < float(load.max() / load.mean())
    # hot ids are never moved
    ids2, _o, _p = plan_migration(
        load, cands, num_shards=8, max_moves=16, target=1.05,
        exclude=[c[0] for c in cands])
    assert ids2.size == 0
    # a balanced vector plans nothing
    ids3, _o3, _p3 = plan_migration(
        np.full((8,), 100.0), cands, num_shards=8, max_moves=16,
        target=1.05)
    assert ids3.size == 0


def test_migrate_rows_keeps_hot_and_migrated_disjoint():
    """`migrate_rows` drops ids currently hot; `refresh_hot_rows` skips ids
    currently migrated — mechanically, whatever the caller passes."""
    rng = np.random.default_rng(3)
    b = {"sparse": {"a": rng.integers(0, VOCAB, (B, 4)).astype(np.int32)},
         "label": rng.integers(0, 2, (B,)).astype(np.float32)}
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="fp32", hot_rows=4, mig_rows=8)
    state = tr.init(b)
    state = tr.refresh_hot_rows(state, hot_ids={"a": np.asarray([7, 13])})
    # 7 is hot: the move list must drop it
    state = tr.migrate_rows(state, {"a": (np.asarray([7, 21]),
                                          np.asarray([0, 1]))})
    mig_ids = tr._np_id_list(state.tables["a"].mig.ids)
    assert mig_ids.tolist() == [21]
    # 21 is migrated: promotion must skip it
    state = tr.refresh_hot_rows(state, hot_ids={"a": np.asarray([21, 33])})
    hot_ids = tr._np_id_list(state.tables["a"].hot.ids)
    assert 21 not in hot_ids.tolist() and 33 in hot_ids.tolist()


# ---------------------------------------------------------------------------
# skew_report --recommend (the offline policy dry run)
# ---------------------------------------------------------------------------


def test_skew_report_recommend_from_scrape(tmp_path, capsys):
    """The --recommend dry run reconstructs the policy inputs from a saved
    /metrics scrape and prints per-table H, predicted hit ratio and the
    migration plan — the operator's audit surface before enabling the
    controller."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools import skew_report

    # publish sketch + exchange gauges the way a live node does; 16 heavy
    # ids homed on shard 5 while the budget fits 8 -> the other 8 are the
    # heavy-but-not-hot cold tail the migration plan must move
    mon = SkewMonitor(k=32, sync=True)
    ids = np.concatenate([np.repeat((np.arange(16) * S + 5),
                                    np.arange(60, 28, -2)),
                          np.arange(200)])
    mon.observe("a", ids)
    mon.publish()
    for shard, v in enumerate([30, 30, 30, 30, 30, 300, 30, 30]):
        metrics.observe("exchange.shard_positions", float(v), "gauge",
                        labels={"table": "a", "shard": str(shard)})
    metrics.observe("exchange.row_dim", 8.0, "gauge", labels={"table": "a"})
    scrape = tmp_path / "metrics.txt"
    scrape.write_text(metrics.prometheus_text())

    rc = skew_report.main([str(scrape), "--recommend",
                           "--hot-budget-kb", "0.5", "--mig-rows", "8"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "placement recommendation" in out
    assert "hot_rows=" in out and "predicted_hit=" in out
    assert "migration_plan=" in out and "move id=" in out


def test_controller_background_watcher_parks_decisions():
    """The watcher thread computes decisions off the training thread and
    parks them; `on_step` applies the parked decision even off-cadence."""
    mon = SkewMonitor(k=32, sync=True)
    mon.observe("a", np.repeat((np.arange(8) * S + 5), 50))
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="fp32")
    policy = PlacementPolicy(4 * row_bytes(DIM, 1),
                             refresh_cooldown_steps=0)
    ctrl = PlacementController(tr, policy, monitor=mon,
                               interval_steps=10**9)  # inline path disabled
    b = {"sparse": {"a": np.repeat((np.arange(8) * S + 5),
                                   8).reshape(B, 1).astype(np.int32)[:B]},
         "label": np.zeros((B,), np.float32)}
    state = tr.init(b)
    state = ctrl.prime(state)
    ctrl.start(interval_s=0.05)
    try:
        deadline = 50
        pending = None
        import time as _time
        for _ in range(deadline):
            _time.sleep(0.1)
            with ctrl._lock:
                pending = ctrl._pending
            if pending is not None:
                break
        assert pending is not None, "watcher never parked a decision"
    finally:
        ctrl.stop()
    state = ctrl.on_step(state, step=1)  # off-cadence: applies the parked one
    assert state.tables["a"].hot is not None
