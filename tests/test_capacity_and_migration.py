"""capacity_factor under skew (overflow counters must FIRE and training must
survive), the num_shards honesty warning, and optimizer-swap slot migration at
checkpoint load (tables AND dense tower).

Reference anchors: the PS's unbounded per-request buffers
(`EmbeddingPullOperator.cpp:86-112` — our static capacities must be *managed*,
not just counted), `WorkerContext.cpp:66-85` (num_shards placement),
`EmbeddingVariable.cpp:29-60` (`copy_from` optimizer/table hot-swap)."""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.parallel import MeshTrainer, make_mesh

S = 8
VOCAB = 1 << 14


def _skewed_batch(B=64, fields=4, seed=0):
    """Every id owned by shard 0 (id % S == 0) — the adversarial case for
    per-(src,dst) bucket capacities."""
    rng = np.random.default_rng(seed)
    ids = (rng.integers(0, VOCAB // S, size=(B, fields)) * S).astype(np.int64)
    labels = (rng.random(B) < 0.5).astype(np.float32)
    return {"sparse": {"categorical": ids}, "label": labels}


def _trainer(capacity_factor):
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,))
    return MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                       mesh=make_mesh(), capacity_factor=capacity_factor)


def test_capacity_factor_overflow_fires_and_training_survives():
    """f=0.5 with single-shard-owner skew: the (src, 0) buckets are ~S/2x too
    small, pull_overflow/push_overflow MUST fire, and the step must stay
    finite (dropped ids pull zeros / drop grads, never corrupt)."""
    tr = _trainer(0.5)
    b = _skewed_batch()
    state = tr.init(b)
    step = tr.jit_train_step(b, state)
    state, m = step(state, b)
    assert np.isfinite(float(m["loss"]))
    assert int(m["stats"]["categorical/pull_overflow"]) > 0
    assert int(m["stats"]["categorical/push_overflow"]) > 0
    # training continues across steps despite sustained overflow
    for seed in (1, 2):
        state, m = step(state, _skewed_batch(seed=seed))
        assert np.isfinite(float(m["loss"]))


def test_capacity_factor_exact_mode_never_drops():
    """f=0 (exact, cap=n) on the same skewed stream: zero overflow."""
    tr = _trainer(0.0)
    b = _skewed_batch()
    state = tr.init(b)
    state, m = tr.jit_train_step(b, state)(state, b)
    assert int(m["stats"]["categorical/pull_overflow"]) == 0
    assert int(m["stats"]["categorical/push_overflow"]) == 0


def test_capacity_factor_sizing_rule_uniform():
    """Uniform ids at f=1.0: cap = n/S >= u/S per bucket holds with huge
    probability at these sizes -> no drops (the documented sizing rule)."""
    tr = _trainer(1.0)
    b = next(synthetic_criteo(64, id_space=VOCAB, steps=1, seed=3))
    state = tr.init(b)
    state, m = tr.jit_train_step(b, state)(state, b)
    assert np.isfinite(float(m["loss"]))
    # Zipf-hashed ids at f=1.0 may drop a little on the hottest shard; the
    # counters make it visible either way
    assert int(m["stats"]["categorical/pull_overflow"]) >= 0


def test_on_overflow_grow_adapts_until_zero_drops():
    """Adaptive capacity (round 5): on_overflow='grow' doubles
    capacity_factor on every overflowing window and invalidates the compiled
    step; on the adversarial single-owner stream f climbs 1 -> 8 (= S, the
    exact-capacity ceiling) and drops reach ZERO — the managed answer to the
    reference's can't-drop dynamic buffers (`EmbeddingPullOperator.cpp:86-112`)."""
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,))
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), capacity_factor=1.0,
                     on_overflow="grow")
    b = _skewed_batch()
    state = tr.init(b)
    step = tr.jit_train_step(b, state)
    factors = [tr.capacity_factor]
    for i in range(8):
        state, m = step(state, _skewed_batch(seed=i))
        if tr.check_overflow(m):
            factors.append(tr.capacity_factor)
            step = tr.jit_train_step(b, state)  # recompile, bigger buckets
    assert factors[-1] == float(S), factors  # grew to the exact ceiling
    state, m = step(state, _skewed_batch(seed=99))
    assert tr.overflow_count(m) == 0, dict(m["stats"])
    # and grown-capacity training still converges on a fixed batch
    fixed = _skewed_batch(seed=7)
    losses = []
    for _ in range(30):
        state, m = step(state, fixed)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses[::10]


def test_on_overflow_raise_fails_loud():
    """on_overflow='raise': the first overflowing window raises with the drop
    count and the sizing-rule pointer instead of silently training without
    the dropped rows."""
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,))
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), capacity_factor=1.0,
                     on_overflow="raise")
    b = _skewed_batch()
    state = tr.init(b)
    state, m = tr.jit_train_step(b, state)(state, b)
    with pytest.raises(RuntimeError, match="capacity_factor"):
        tr.check_overflow(m)
    with pytest.raises(ValueError, match="on_overflow"):
        MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                    mesh=make_mesh(), on_overflow="explode")


def test_train_many_reports_window_overflow():
    """The scan path returns no per-step stats; its metrics carry ONE summed
    'overflow' scalar so window-level governance (and bench reporting) see
    the drops."""
    import jax as _jax

    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,))
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), capacity_factor=1.0)
    batches = [_skewed_batch(seed=s) for s in range(4)]
    stacked = _jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
    state = tr.init(batches[0])
    many = tr.jit_train_many(stacked, state)
    state, m = many(state, stacked)
    assert tr.overflow_count(m) > 0
    # exact mode: same window, zero drops
    tr0 = MeshTrainer(make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,)),
                      embed.Adagrad(learning_rate=0.1), mesh=make_mesh(),
                      capacity_factor=0.0)
    state0 = tr0.init(batches[0])
    many0 = tr0.jit_train_many(stacked, state0)
    state0, m0 = many0(state0, stacked)
    assert tr0.overflow_count(m0) == 0


def test_zipfian_f1_drop_rate_and_auc_vs_exact():
    """The PRODUCTION capacity config (f=1.0, bench mesh1f) on the traffic it
    will actually see — Zipfian planted-signal streams — measured, not
    assumed. At this deliberately small per-device batch (256 ids/device ->
    32-id buckets, worst-case relative fluctuation; bench's 106k-id batches
    sit far inside the sizing rule) the measured reality is: static f=1.0
    drops ~3.9% of id positions and costs ~0.005 AUC; on_overflow='grow'
    confines drops to the first windows (~1.3% total, declining) and
    recovers the AUC to within noise of exact mode. Pins below bound those
    measurements with margin."""
    from openembedding_tpu.data import planted_criteo
    from openembedding_tpu.models import make_lr
    from openembedding_tpu.utils.metrics import auc

    BATCH, STEPS, EPOCHS = 256, 100, 3
    heldout = list(planted_criteo(BATCH, steps=10, seed=999))
    labels = np.concatenate([b["label"] for b in heldout])

    def run(factor, grow=False):
        tr = MeshTrainer(make_lr(vocabulary=1 << 15),
                         embed.Adam(learning_rate=0.02), mesh=make_mesh(),
                         capacity_factor=factor,
                         on_overflow="grow" if grow else "count")
        state, many, dropped, total = None, None, 0, 0
        for epoch in range(EPOCHS):
            batches = list(planted_criteo(BATCH, steps=STEPS, seed=epoch))
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *batches)
            if state is None:
                state = tr.init(batches[0])
                many = tr.jit_train_many(stacked, state)
            state, m = many(state, stacked)
            dropped += tr.overflow_count(m)
            total += sum(b["sparse"]["categorical"].size for b in batches)
            if grow and tr.check_overflow(m):
                many = tr.jit_train_many(stacked, state)  # recompiled
        ev = tr.jit_eval_step(heldout[0], state)
        scores = np.concatenate(
            [np.asarray(ev(state, b)["logits"]).reshape(-1) for b in heldout])
        return auc(labels, scores), dropped, total

    auc_exact, drop_exact, _ = run(0.0)
    auc_f1, drop_f1, total = run(1.0)
    auc_grow, drop_grow, _ = run(1.0, grow=True)
    assert drop_exact == 0
    # static f=1.0: drops visible and bounded (measured 3.9%)
    assert 0 < drop_f1 / total < 0.06, (drop_f1, total)
    assert auc_f1 > auc_exact - 0.01, (auc_f1, auc_exact, drop_f1)
    # adaptive: strictly fewer drops than static, AUC within noise of exact
    assert drop_grow < drop_f1, (drop_grow, drop_f1)
    assert auc_grow > auc_exact - 0.005, (auc_grow, auc_exact, drop_grow)


def test_num_shards_mismatch_warns():
    """A num_shards value that cannot be honored must warn, not lie
    (VERDICT r2 weak #5)."""
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,), num_shards=3)
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh())
    b = next(synthetic_criteo(16, id_space=VOCAB, steps=1, seed=0))
    with pytest.warns(UserWarning, match="num_shards=3 is not honored"):
        tr.init(b)
    # -1 and the mesh size itself stay silent
    model2 = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,), num_shards=-1)
    tr2 = MeshTrainer(model2, embed.Adagrad(learning_rate=0.1),
                      mesh=make_mesh())
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        tr2.init(b)


# ---------------------------------------------------------------------------
# optimizer-swap migration at checkpoint load
# ---------------------------------------------------------------------------


def _train_one(optimizer, b, mesh=None):
    model = make_deepfm(vocabulary=256, dim=4, hidden=(8,))
    tr = (MeshTrainer(model, optimizer, mesh=mesh) if mesh
          else Trainer(model, optimizer))
    st = tr.init(b)
    step = tr.jit_train_step(b, st) if mesh else tr.jit_train_step()
    st, _ = step(st, b)
    return tr, st


@pytest.mark.parametrize("sharded", [False, True])
def test_optimizer_swap_migrates_compatible_slots(tmp_path, sharded):
    """Adagrad checkpoint -> Adadelta trainer: the shared 'accum' slot carries
    (tables and dense tower), 'accum_update' takes fresh init, and the next
    step RUNS (wholesale dense-slot replacement used to KeyError inside jit)."""
    b = next(synthetic_criteo(16, id_space=256, steps=1, seed=0))
    mesh = make_mesh() if sharded else None
    tr, st = _train_one(
        embed.Adagrad(learning_rate=0.1, initial_accumulator_value=0.1),
        b, mesh)
    accum = np.asarray(st.tables["categorical"].slots["accum"])
    path = str(tmp_path / "ck")
    tr.save(st, path)

    tr2_model = make_deepfm(vocabulary=256, dim=4, hidden=(8,))
    tr2 = (MeshTrainer(tr2_model, embed.Adadelta(learning_rate=0.1),
                       mesh=mesh) if sharded
           else Trainer(tr2_model, embed.Adadelta(learning_rate=0.1)))
    st2 = tr2.init(b)
    st2 = tr2.load(st2, path)
    np.testing.assert_allclose(
        np.asarray(st2.tables["categorical"].slots["accum"]), accum,
        rtol=0, atol=0)
    assert (np.asarray(
        st2.tables["categorical"].slots["accum_update"]) == 0).all()
    step2 = tr2.jit_train_step(b, st2) if sharded else tr2.jit_train_step()
    st2, m = step2(st2, b)
    assert np.isfinite(float(m["loss"]))


def test_optimizer_swap_incompatible_slots_reset(tmp_path):
    """Adagrad -> Momentum: no shared slot names; everything takes fresh init
    and training still proceeds (the reference resets states on category
    change the same way)."""
    b = next(synthetic_criteo(16, id_space=256, steps=1, seed=1))
    tr, st = _train_one(embed.Adagrad(learning_rate=0.1), b)
    path = str(tmp_path / "ck")
    tr.save(st, path)

    tr2 = Trainer(make_deepfm(vocabulary=256, dim=4, hidden=(8,)),
                  embed.Momentum(learning_rate=0.1, momentum=0.9))
    st2 = tr2.init(b)
    st2 = tr2.load(st2, path)
    assert (np.asarray(st2.tables["categorical"].slots["moment"]) == 0).all()
    st2, m = tr2.jit_train_step()(st2, b)
    assert np.isfinite(float(m["loss"]))


def test_same_optimizer_roundtrip_unchanged(tmp_path):
    """Control: same optimizer reloads bit-identically (migration must not
    perturb the fast path)."""
    b = next(synthetic_criteo(16, id_space=256, steps=1, seed=2))
    tr, st = _train_one(embed.Adagrad(learning_rate=0.1), b)
    path = str(tmp_path / "ck")
    tr.save(st, path)
    tr2 = Trainer(make_deepfm(vocabulary=256, dim=4, hidden=(8,)),
                  embed.Adagrad(learning_rate=0.1))
    st2 = tr2.init(b)
    st2 = tr2.load(st2, path)
    np.testing.assert_array_equal(
        np.asarray(st2.tables["categorical"].slots["accum"]),
        np.asarray(st.tables["categorical"].slots["accum"]))
    flat1 = jax.tree_util.tree_leaves(st.dense_slots)
    flat2 = jax.tree_util.tree_leaves(st2.dense_slots)
    for a, c in zip(flat1, flat2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
