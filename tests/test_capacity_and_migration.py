"""capacity_factor under skew (overflow counters must FIRE and training must
survive), the num_shards honesty warning, and optimizer-swap slot migration at
checkpoint load (tables AND dense tower).

Reference anchors: the PS's unbounded per-request buffers
(`EmbeddingPullOperator.cpp:86-112` — our static capacities must be *managed*,
not just counted), `WorkerContext.cpp:66-85` (num_shards placement),
`EmbeddingVariable.cpp:29-60` (`copy_from` optimizer/table hot-swap)."""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.parallel import MeshTrainer, make_mesh

S = 8
VOCAB = 1 << 14


def _skewed_batch(B=64, fields=4, seed=0):
    """Every id owned by shard 0 (id % S == 0) — the adversarial case for
    per-(src,dst) bucket capacities."""
    rng = np.random.default_rng(seed)
    ids = (rng.integers(0, VOCAB // S, size=(B, fields)) * S).astype(np.int64)
    labels = (rng.random(B) < 0.5).astype(np.float32)
    return {"sparse": {"categorical": ids}, "label": labels}


def _trainer(capacity_factor):
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,))
    return MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                       mesh=make_mesh(), capacity_factor=capacity_factor)


def test_capacity_factor_overflow_fires_and_training_survives():
    """f=0.5 with single-shard-owner skew: the (src, 0) buckets are ~S/2x too
    small, pull_overflow/push_overflow MUST fire, and the step must stay
    finite (dropped ids pull zeros / drop grads, never corrupt)."""
    tr = _trainer(0.5)
    b = _skewed_batch()
    state = tr.init(b)
    step = tr.jit_train_step(b, state)
    state, m = step(state, b)
    assert np.isfinite(float(m["loss"]))
    assert int(m["stats"]["categorical/pull_overflow"]) > 0
    assert int(m["stats"]["categorical/push_overflow"]) > 0
    # training continues across steps despite sustained overflow
    for seed in (1, 2):
        state, m = step(state, _skewed_batch(seed=seed))
        assert np.isfinite(float(m["loss"]))


def test_capacity_factor_exact_mode_never_drops():
    """f=0 (exact, cap=n) on the same skewed stream: zero overflow."""
    tr = _trainer(0.0)
    b = _skewed_batch()
    state = tr.init(b)
    state, m = tr.jit_train_step(b, state)(state, b)
    assert int(m["stats"]["categorical/pull_overflow"]) == 0
    assert int(m["stats"]["categorical/push_overflow"]) == 0


def test_capacity_factor_sizing_rule_uniform():
    """Uniform ids at f=1.0: cap = n/S >= u/S per bucket holds with huge
    probability at these sizes -> no drops (the documented sizing rule)."""
    tr = _trainer(1.0)
    b = next(synthetic_criteo(64, id_space=VOCAB, steps=1, seed=3))
    state = tr.init(b)
    state, m = tr.jit_train_step(b, state)(state, b)
    assert np.isfinite(float(m["loss"]))
    # Zipf-hashed ids at f=1.0 may drop a little on the hottest shard; the
    # counters make it visible either way
    assert int(m["stats"]["categorical/pull_overflow"]) >= 0


def test_num_shards_mismatch_warns():
    """A num_shards value that cannot be honored must warn, not lie
    (VERDICT r2 weak #5)."""
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,), num_shards=3)
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh())
    b = next(synthetic_criteo(16, id_space=VOCAB, steps=1, seed=0))
    with pytest.warns(UserWarning, match="num_shards=3 is not honored"):
        tr.init(b)
    # -1 and the mesh size itself stay silent
    model2 = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,), num_shards=-1)
    tr2 = MeshTrainer(model2, embed.Adagrad(learning_rate=0.1),
                      mesh=make_mesh())
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        tr2.init(b)


# ---------------------------------------------------------------------------
# optimizer-swap migration at checkpoint load
# ---------------------------------------------------------------------------


def _train_one(optimizer, b, mesh=None):
    model = make_deepfm(vocabulary=256, dim=4, hidden=(8,))
    tr = (MeshTrainer(model, optimizer, mesh=mesh) if mesh
          else Trainer(model, optimizer))
    st = tr.init(b)
    step = tr.jit_train_step(b, st) if mesh else tr.jit_train_step()
    st, _ = step(st, b)
    return tr, st


@pytest.mark.parametrize("sharded", [False, True])
def test_optimizer_swap_migrates_compatible_slots(tmp_path, sharded):
    """Adagrad checkpoint -> Adadelta trainer: the shared 'accum' slot carries
    (tables and dense tower), 'accum_update' takes fresh init, and the next
    step RUNS (wholesale dense-slot replacement used to KeyError inside jit)."""
    b = next(synthetic_criteo(16, id_space=256, steps=1, seed=0))
    mesh = make_mesh() if sharded else None
    tr, st = _train_one(
        embed.Adagrad(learning_rate=0.1, initial_accumulator_value=0.1),
        b, mesh)
    accum = np.asarray(st.tables["categorical"].slots["accum"])
    path = str(tmp_path / "ck")
    tr.save(st, path)

    tr2_model = make_deepfm(vocabulary=256, dim=4, hidden=(8,))
    tr2 = (MeshTrainer(tr2_model, embed.Adadelta(learning_rate=0.1),
                       mesh=mesh) if sharded
           else Trainer(tr2_model, embed.Adadelta(learning_rate=0.1)))
    st2 = tr2.init(b)
    st2 = tr2.load(st2, path)
    np.testing.assert_allclose(
        np.asarray(st2.tables["categorical"].slots["accum"]), accum,
        rtol=0, atol=0)
    assert (np.asarray(
        st2.tables["categorical"].slots["accum_update"]) == 0).all()
    step2 = tr2.jit_train_step(b, st2) if sharded else tr2.jit_train_step()
    st2, m = step2(st2, b)
    assert np.isfinite(float(m["loss"]))


def test_optimizer_swap_incompatible_slots_reset(tmp_path):
    """Adagrad -> Momentum: no shared slot names; everything takes fresh init
    and training still proceeds (the reference resets states on category
    change the same way)."""
    b = next(synthetic_criteo(16, id_space=256, steps=1, seed=1))
    tr, st = _train_one(embed.Adagrad(learning_rate=0.1), b)
    path = str(tmp_path / "ck")
    tr.save(st, path)

    tr2 = Trainer(make_deepfm(vocabulary=256, dim=4, hidden=(8,)),
                  embed.Momentum(learning_rate=0.1, momentum=0.9))
    st2 = tr2.init(b)
    st2 = tr2.load(st2, path)
    assert (np.asarray(st2.tables["categorical"].slots["moment"]) == 0).all()
    st2, m = tr2.jit_train_step()(st2, b)
    assert np.isfinite(float(m["loss"]))


def test_same_optimizer_roundtrip_unchanged(tmp_path):
    """Control: same optimizer reloads bit-identically (migration must not
    perturb the fast path)."""
    b = next(synthetic_criteo(16, id_space=256, steps=1, seed=2))
    tr, st = _train_one(embed.Adagrad(learning_rate=0.1), b)
    path = str(tmp_path / "ck")
    tr.save(st, path)
    tr2 = Trainer(make_deepfm(vocabulary=256, dim=4, hidden=(8,)),
                  embed.Adagrad(learning_rate=0.1))
    st2 = tr2.init(b)
    st2 = tr2.load(st2, path)
    np.testing.assert_array_equal(
        np.asarray(st2.tables["categorical"].slots["accum"]),
        np.asarray(st.tables["categorical"].slots["accum"]))
    flat1 = jax.tree_util.tree_leaves(st.dense_slots)
    flat2 = jax.tree_util.tree_leaves(st2.dense_slots)
    for a, c in zip(flat1, flat2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
