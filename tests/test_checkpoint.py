"""Checkpoint round-trip tests incl. topology change (the reference's e2e sweep covers
checkpoint at np=2 -> restore at np=8, `build.sh:91-150`; SURVEY.md §4 implication (c))."""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.parallel import MeshTrainer, deinterleave_rows, make_mesh

S = 8


class TinyDense(nn.Module):
    @nn.compact
    def __call__(self, embedded, dense_inputs):
        parts = [embedded[k].reshape(embedded[k].shape[0], -1)
                 for k in sorted(embedded)]
        x = jnp.concatenate(parts, axis=-1)
        return nn.Dense(1)(x)[:, 0]


def make_batch(rng, vocab, B, hash_ids=False):
    if hash_ids:
        ids = rng.integers(0, 2**61, size=(B, 3), dtype=np.int64)
    else:
        ids = rng.integers(0, vocab, size=(B, 3))
    y = (ids.sum(axis=1) % 2).astype(np.float32)
    return {"sparse": {"emb": jnp.asarray(ids)}, "label": jnp.asarray(y)}


def build(vocab, trainer_cls, capacity=0, **kw):
    layer = embed.Embedding(vocab, 8, name="emb", capacity=capacity)
    model = embed.EmbeddingModel(TinyDense(), [layer])
    return embed.Trainer(model, optimizer=embed.Adagrad(learning_rate=0.05)) \
        if trainer_cls is embed.Trainer else \
        trainer_cls(model, optimizer=embed.Adagrad(learning_rate=0.05), **kw)


def train_some(trainer, batch, steps=10, mesh=False):
    state = trainer.init(batch)
    step = (trainer.jit_train_step(batch, state) if mesh
            else trainer.jit_train_step())
    for _ in range(steps):
        state, m = step(state, batch)
    return state, m


def test_mesh_to_single_roundtrip(tmp_path):
    """Train on 8-way mesh, save, restore into a single-device trainer: every id's row
    and optimizer slot must match exactly."""
    rng = np.random.default_rng(0)
    vocab = 201  # deliberately not divisible by 8 (padding rows in play)
    mesh = make_mesh()
    tr_mesh = build(vocab, MeshTrainer, mesh=mesh)
    batch = make_batch(rng, vocab, 16 * S)
    state, _ = train_some(tr_mesh, batch, mesh=True)
    tr_mesh.save(state, str(tmp_path / "ckpt"))

    tr_one = build(vocab, embed.Trainer)
    st1 = tr_one.init(batch)
    st1 = tr_one.load(st1, str(tmp_path / "ckpt"))

    expect_w = deinterleave_rows(np.asarray(state.tables["emb"].weights), S, vocab)
    np.testing.assert_array_equal(np.asarray(st1.tables["emb"].weights), expect_w)
    expect_a = deinterleave_rows(np.asarray(state.tables["emb"].slots["accum"]),
                                 S, vocab)
    np.testing.assert_array_equal(np.asarray(st1.tables["emb"].slots["accum"]),
                                  expect_a)
    # dense params too
    for a, b in zip(jax.tree_util.tree_leaves(state.dense_params),
                    jax.tree_util.tree_leaves(st1.dense_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(st1.step) == 10


def test_single_to_mesh_roundtrip(tmp_path):
    """Reverse direction: single-device training restored onto the mesh; sharded
    lookups must return the same rows."""
    rng = np.random.default_rng(1)
    vocab = 100
    tr_one = build(vocab, embed.Trainer)
    batch = make_batch(rng, vocab, 16 * S)
    state1, _ = train_some(tr_one, batch)
    tr_one.save(state1, str(tmp_path / "ckpt"))

    mesh = make_mesh()
    tr_mesh = build(vocab, MeshTrainer, mesh=mesh)
    st = tr_mesh.init(batch)
    st = tr_mesh.load(st, str(tmp_path / "ckpt"))
    got = deinterleave_rows(np.asarray(st.tables["emb"].weights), S, vocab)
    np.testing.assert_array_equal(got, np.asarray(state1.tables["emb"].weights))
    # and the restored mesh state keeps training
    step = tr_mesh.jit_train_step(batch, st)
    st, m = step(st, batch)
    assert np.isfinite(float(m["loss"]))


def test_hash_table_topology_change(tmp_path):
    """Hash-table variables: mesh-trained keys re-inserted into a single-device table;
    every trained id must read back its exact row."""
    rng = np.random.default_rng(2)
    mesh = make_mesh()
    tr_mesh = build(-1, MeshTrainer, capacity=4096, mesh=mesh)
    batch = make_batch(rng, -1, 16 * S, hash_ids=True)
    state, _ = train_some(tr_mesh, batch, mesh=True)
    tr_mesh.save(state, str(tmp_path / "ckpt"))

    tr_one = build(-1, embed.Trainer, capacity=4096)
    st1 = tr_one.init(batch)
    st1 = tr_one.load(st1, str(tmp_path / "ckpt"))
    assert int(st1.tables["emb"].overflow) == 0

    ids = np.unique(np.asarray(batch["sparse"]["emb"]).reshape(-1))
    from openembedding_tpu.embedding import lookup
    got = np.asarray(lookup(tr_one.model.specs["emb"], st1.tables["emb"],
                            jnp.asarray(ids)))
    want = np.asarray(tr_mesh.jit_eval_step(batch, state)(
        state, batch))  # not comparable directly; instead compare via mesh lookup
    # simpler oracle: the compacted dump itself (MeshTrainer.save writes the
    # per-shard streaming layout, one id-sorted (ids, weights) pair per shard)
    import os
    vdir = tmp_path / "ckpt" / "variable_0"
    dumped_ids, dumped_w = [], []
    for sd in sorted(os.listdir(vdir)):
        dumped_ids.append(np.load(vdir / sd / "ids.npy"))
        dumped_w.append(np.load(vdir / sd / "weights.npy"))
    dumped_ids = np.concatenate(dumped_ids)
    dumped_w = np.concatenate(dumped_w)
    lut = {int(i): dumped_w[k] for k, i in enumerate(dumped_ids)}
    for k, i in enumerate(ids):
        np.testing.assert_array_equal(got[k], lut[int(i)], err_msg=f"id {i}")


def test_include_optimizer_false_resets_slots(tmp_path):
    rng = np.random.default_rng(3)
    vocab = 50
    tr = build(vocab, embed.Trainer)
    batch = make_batch(rng, vocab, 32)
    state, _ = train_some(tr, batch)
    tr.save(state, str(tmp_path / "ckpt"), include_optimizer=False)
    st2 = tr.init(batch)
    st2 = tr.load(st2, str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(np.asarray(st2.tables["emb"].weights),
                                  np.asarray(state.tables["emb"].weights))
    # slots kept their fresh init (reference resets optimizer state too)
    np.testing.assert_allclose(np.asarray(st2.tables["emb"].slots["accum"]), 0.1)
