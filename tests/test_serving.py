"""Standalone export + serving registry/manager/REST tests.

Mirrors the reference's serving coverage: save_as_original_model round-trip
(`tensorflow/exb.py:506-547`), ModelManager CREATING-refusal
(`client/ModelController.cpp:24-44`), controller REST admin
(`entry/controller.cc:100-205`) and the serving pull path (`exb_ops.cpp:261-276`).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.export import StandaloneModel, export_standalone
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.serving import (ModelManager, ModelRegistry, make_server,
                                       resolve_sign)


VOCAB = 1 << 10


@pytest.fixture(scope="module")
def trained():
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=3)
    batches = list(synthetic_criteo(32, id_space=VOCAB, steps=3, seed=5))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    for b in batches:
        state, _ = step(state, b)
    return model, trainer, state, batches[0]


def test_resolve_sign():
    assert resolve_sign("abc", 3.7) == "abc-3"
    assert resolve_sign("abc", 0.0) == "abc-0"


def test_export_and_lookup_parity(trained, tmp_path):
    model, trainer, state, batch = trained
    path = str(tmp_path / "export")
    meta = export_standalone(state, model, path, model_sign="m-0")
    assert meta.model_sign == "m-0"

    sm = StandaloneModel.load(path)
    # exported rows == live table rows (S=1: global row order == id order)
    ids = np.arange(0, 50, dtype=np.int64)
    live = np.asarray(state.tables["categorical"].weights)[:50]
    got = np.asarray(sm.lookup("categorical", ids))
    np.testing.assert_array_equal(live, got)
    # out-of-range ids -> zeros (read-only serving semantics)
    oob = np.asarray(sm.lookup("categorical", np.asarray([VOCAB + 5, -3])))
    assert (oob == 0).all()


def test_export_predict_matches_eval(trained, tmp_path):
    model, trainer, state, batch = trained
    path = str(tmp_path / "export2")
    export_standalone(state, model, path)
    sm = StandaloneModel.load(path)  # module rebuilt from model_config recipe
    want = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
    got = np.asarray(sm.predict(batch))
    np.testing.assert_allclose(want, got, rtol=1e-5, atol=1e-5)


def test_export_hash_table(tmp_path):
    from openembedding_tpu.embedding import (EmbeddingSpec, init_table_state,
                                             lookup_train)
    from openembedding_tpu.model import EmbeddingModel, TrainState
    from openembedding_tpu.models.ctr import LogisticRegression
    from openembedding_tpu.embedding import Embedding

    emb = Embedding(input_dim=-1, output_dim=1, name="categorical", capacity=64)
    model = EmbeddingModel(LogisticRegression(), [emb])
    spec = model.specs["categorical"]
    opt = embed.Adagrad()
    table = init_table_state(spec, opt)
    ids = jnp.asarray(np.asarray([7, 1 << 40, 12345], np.int64))
    table, _ = lookup_train(spec, table, ids)
    state = TrainState(step=jnp.zeros((), jnp.int32), dense_params={},
                       dense_slots={}, tables={"categorical": table},
                       model_version=jnp.zeros((), jnp.int32))
    path = str(tmp_path / "hash_export")
    export_standalone(state, model, path)
    sm = StandaloneModel.load(path)
    got = np.asarray(sm.lookup("categorical", ids))
    want = np.asarray(
        __import__("openembedding_tpu.embedding", fromlist=["lookup"]).lookup(
            spec, table, ids))
    np.testing.assert_array_equal(want, got)
    # absent id -> zeros
    assert (np.asarray(sm.lookup("categorical", jnp.asarray([999]))) == 0).all()


def test_registry_lifecycle(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    entry = reg.create_model("sig-1", "/nonexistent", replica_num=2, shard_num=4)
    assert entry["status"] == "CREATING"
    # manager refuses CREATING models (reference ModelManager parity)
    mgr = ModelManager(reg)
    with pytest.raises(RuntimeError, match="CREATING"):
        mgr.find_model("sig-1")
    # NORMAL entries refuse re-create; CREATING entries may be overwritten
    reg.create_model("sig-1", "/other")
    reg.set_status("sig-1", "NORMAL")
    with pytest.raises(FileExistsError):
        reg.create_model("sig-1", "/x")
    assert set(reg.show_models()) == {"sig-1"}
    reg.delete_model("sig-1")
    assert reg.show_models() == {}
    with pytest.raises(KeyError):
        reg.set_status("sig-1", "NORMAL")


def test_manager_load_error_records_status(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg2"))
    mgr = ModelManager(reg)
    with pytest.raises(Exception):
        mgr.load_model("bad", str(tmp_path / "missing"))
    assert reg.get("bad")["status"] == "ERROR"
    assert reg.get("bad")["error"]


@pytest.fixture()
def server(tmp_path):
    httpd = make_server(str(tmp_path / "registry"), port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", httpd
    httpd.shutdown()


def _req(url, method="GET", payload=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rest_round_trip(trained, tmp_path, server):
    model, trainer, state, batch = trained
    base, httpd = server
    export_path = str(tmp_path / "rest_export")
    export_standalone(state, model, export_path, model_sign="rest-0")

    status, body = _req(f"{base}/healthz")
    assert status == 200 and body["status"] == "ok"

    # controller parity: POST /models {model_sign, model_uri, replica_num, shard_num}
    status, entry = _req(f"{base}/models", "POST",
                         {"model_sign": "rest-0", "model_uri": export_path,
                          "replica_num": 1, "shard_num": 1})
    assert status == 200 and entry["status"] == "NORMAL"

    status, models = _req(f"{base}/models")
    assert status == 200 and "rest-0" in models

    # serving pull (read-only PullWeights path)
    ids = [0, 1, 5, VOCAB + 9]
    status, out = _req(f"{base}/models/rest-0/pull", "POST",
                       {"variable": "categorical", "ids": ids})
    assert status == 200
    rows = np.asarray(out["weights"], np.float32)
    live = np.asarray(state.tables["categorical"].weights)
    np.testing.assert_allclose(rows[:3], live[[0, 1, 5]], rtol=1e-6)
    assert (rows[3] == 0).all()

    # predict end to end over HTTP
    status, out = _req(
        f"{base}/models/rest-0/predict", "POST",
        {"sparse": {"categorical": np.asarray(batch["sparse"]["categorical"])
                    .tolist()},
         "dense": np.asarray(batch["dense"]).tolist()})
    assert status == 200
    want = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
    np.testing.assert_allclose(np.asarray(out["logits"]), want,
                               rtol=1e-4, atol=1e-4)

    status, nodes = _req(f"{base}/nodes")
    assert status == 200 and len(nodes["nodes"]) == 1

    status, _ = _req(f"{base}/models/rest-0", "DELETE")
    assert status == 200
    status, _ = _req(f"{base}/models/rest-0/pull", "POST",
                     {"variable": "categorical", "ids": [1]})
    assert status in (404, 500)

    status, body = _req(f"{base}/models/nope")
    assert status == 404


def test_rest_malformed_body_is_400_not_404(server):
    """Round-1 advisor: a missing required field is the CALLER's error (400);
    404 stays reserved for unknown model/variable signs."""
    base, _ = server
    status, body = _req(f"{base}/models", "POST", {})  # no model_sign
    assert status == 400 and "model_sign" in body["error"]
    status, body = _req(f"{base}/models", "POST", {"model_sign": "x"})  # no uri
    assert status == 400 and "model_uri" in body["error"]
    # unknown model sign on pull is still 404
    status, body = _req(f"{base}/models/nope/pull", "POST",
                        {"variable": "v", "ids": [1]})
    assert status == 404
    # known route, missing ids field -> 400 would need a loaded model; missing
    # "variable" on an unknown model resolves the model first (404) — missing
    # field on /models is the canonical 400 case covered above


def test_predict_micro_batching(trained, tmp_path):
    """N concurrent /predict requests inside one window run as fewer device
    calls (metrics prove aggregation) and every client gets ITS OWN slice."""
    import concurrent.futures
    import urllib.request as _rq

    from openembedding_tpu.export import export_standalone as _export
    from openembedding_tpu.serving import make_server as _mk
    from openembedding_tpu.utils import metrics as _metrics

    model, trainer, state, batch = trained
    path = str(tmp_path / "mb_export")
    _export(state, model, path, model_sign="mb-0")
    srv = _mk(str(tmp_path / "mb_reg"), batch_window_ms=150.0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"

        def post(url, body):
            req = _rq.Request(url, data=json.dumps(body).encode(),
                              method="POST")
            req.add_header("Content-Type", "application/json")
            with _rq.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        post(f"{base}/models", {"model_sign": "mb-0", "model_uri": path})

        ids = np.asarray(batch["sparse"]["categorical"])
        dense = np.asarray(batch["dense"])
        n_req, rows = 6, 4

        def one(i):
            lo = i * rows
            body = {"sparse": {"categorical": ids[lo:lo + rows].tolist()},
                    "dense": dense[lo:lo + rows].tolist()}
            return np.asarray(post(f"{base}/models/mb-0/predict",
                                   body)["logits"])

        b0 = _batches_counter(_metrics)
        with concurrent.futures.ThreadPoolExecutor(n_req) as ex:
            outs = list(ex.map(one, range(n_req)))
        b1 = _batches_counter(_metrics)
        # aggregation happened: far fewer device calls than requests
        assert 1 <= b1 - b0 < n_req

        # per-request correctness against the unbatched model
        sm_logits = np.asarray(
            srv.manager.find_model("mb-0").predict(
                {"sparse": {"categorical": ids[:n_req * rows]},
                 "dense": dense[:n_req * rows]}))
        for i, out in enumerate(outs):
            np.testing.assert_allclose(
                out, sm_logits[i * rows:(i + 1) * rows], rtol=1e-5, atol=1e-5)
    finally:
        srv.shutdown()


def _batches_counter(metrics_mod):
    return metrics_mod.Accumulator.get("serving.predict_batches").value()


def test_serving_client_failover_semantics(trained, tmp_path):
    """ServingClient: dead replicas are skipped; an ANSWERED HTTP error is
    surfaced immediately (never retried on another replica); the starting
    replica rotates per call."""
    import urllib.error

    from openembedding_tpu.export import export_standalone as _export
    from openembedding_tpu.serving import ServingClient, make_server as _mk

    model, trainer, state, batch = trained
    path = str(tmp_path / "sc_export")
    _export(state, model, path, model_sign="sc-0")
    srv = _mk(str(tmp_path / "sc_reg"))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        live = f"http://127.0.0.1:{srv.server_address[1]}"
        dead = "http://127.0.0.1:9"  # discard port: connection refused

        client = ServingClient([dead, live])
        client.create_model("sc-0", path)

        # dead first in rotation: the call still lands on the live node
        rows = client.pull("sc-0", "categorical", [1, 2, 3])
        assert rows.shape == (3, model.specs["categorical"].output_dim)

        # an answered 404 surfaces as HTTPError, not a silent failover loop
        with pytest.raises(urllib.error.HTTPError) as ei:
            client.pull("sc-0", "no_such_variable", [1])
        assert ei.value.code == 404

        # all replicas dead -> ConnectionError naming the nodes
        with pytest.raises(ConnectionError, match="no live replica"):
            ServingClient([dead]).pull("sc-0", "categorical", [1])
    finally:
        srv.shutdown()


def test_binary_pull_negotiation(trained, tmp_path):
    """Accept: application/octet-stream returns npz rows identical to the
    JSON answer (ServingClient binary=True)."""
    from openembedding_tpu.export import export_standalone as _export
    from openembedding_tpu.serving import ServingClient, make_server as _mk

    model, trainer, state, batch = trained
    path = str(tmp_path / "bin_export")
    _export(state, model, path, model_sign="bin-0")
    srv = _mk(str(tmp_path / "bin_reg"))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        client = ServingClient(f"http://127.0.0.1:{srv.server_address[1]}")
        client.create_model("bin-0", path)
        ids = [1, 2, 3, 500]
        js = client.pull("bin-0", "categorical", ids)
        bn = client.pull("bin-0", "categorical", ids, binary=True)
        assert bn.dtype == np.float32
        np.testing.assert_allclose(bn, js, rtol=1e-6, atol=1e-7)
    finally:
        srv.shutdown()


def test_rest_ragged_multivalent_predict(tmp_path, server):
    """Ragged JSON id lists serve end to end: the handler pads each sparse
    feature to its power-of-two field width with -1 (`serving._ids_array`),
    combiner pooling masks the pads out, and the response equals both the
    explicitly padded request and the local StandaloneModel prediction. The
    pull endpoint takes ragged ids the same way (pad rows -> zeros)."""
    from openembedding_tpu.models import make_two_tower

    model = make_two_tower(64, 64, dim=4, tower=(8,), combiner="mean",
                           compute_dtype=jnp.float32)
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    batch = {"sparse": {"user": jnp.asarray([[1, 2], [3, -1]]),
                        "item": jnp.asarray([[5, -1], [6, 7]])},
             "dense": None, "label": None}
    state = trainer.init(batch)
    state, _ = trainer.jit_train_step()(state, batch)

    base, _httpd = server
    path = str(tmp_path / "ragged_export")
    export_standalone(state, model, path, model_sign="rag-0")
    status, _ = _req(f"{base}/models", "POST",
                     {"model_sign": "rag-0", "model_uri": path})
    assert status == 200

    ragged = {"sparse": {"user": [[1, 2], [3]], "item": [[5], [6, 7]]}}
    status, out = _req(f"{base}/models/rag-0/predict", "POST", ragged)
    assert status == 200, out
    got = np.asarray(out["logits"], np.float32)

    padded = {"sparse": {"user": [[1, 2], [3, -1]], "item": [[5, -1], [6, 7]]}}
    status, out2 = _req(f"{base}/models/rag-0/predict", "POST", padded)
    assert status == 200
    np.testing.assert_array_equal(got, np.asarray(out2["logits"], np.float32))

    sm = StandaloneModel.load(path, model=model)
    want = np.asarray(sm.predict(
        {"sparse": {k: np.asarray(v) for k, v in padded["sparse"].items()}}))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert np.isfinite(got).all()

    # a DIFFERENT request width compiles its own bucket and still serves
    status, out3 = _req(f"{base}/models/rag-0/predict", "POST",
                        {"sparse": {"user": [[1, 2, 3], [9]],
                                    "item": [[5], [6, 7, 8]]}})
    assert status == 200 and np.isfinite(np.asarray(out3["logits"])).all()

    # ragged pull: pad rows come back as zeros
    status, out4 = _req(f"{base}/models/rag-0/pull", "POST",
                        {"variable": "user", "ids": [[1, 2, 3], [9]]})
    assert status == 200
    rows = np.asarray(out4["weights"], np.float32)
    assert rows.shape[:2] == (2, 4)  # padded to the power-of-two bucket (4)
    assert (rows[1, 1:] == 0).all() and (rows[0, :3] != 0).any()

    # rectangular input to a POOLED feature width-buckets the same way, so a
    # client pre-padding to width 3 and one sending ragged lists of max len 3
    # hit the SAME compiled program and return the SAME logits
    status, rect = _req(f"{base}/models/rag-0/predict", "POST",
                        {"sparse": {"user": [[1, 2, 3], [9, -1, -1]],
                                    "item": [[5], [6]]}})
    status2, ragg = _req(f"{base}/models/rag-0/predict", "POST",
                         {"sparse": {"user": [[1, 2, 3], [9]],
                                     "item": [[5], [6]]}})
    assert status == 200 and status2 == 200
    np.testing.assert_array_equal(np.asarray(rect["logits"]),
                                  np.asarray(ragg["logits"]))

    # the in-repo client speaks the ragged encoding end to end
    from openembedding_tpu.serving import ServingClient
    client = ServingClient([base])
    via_client = client.predict("rag-0", {"user": [[1, 2], [3]],
                                          "item": [[5], [6, 7]]})
    np.testing.assert_allclose(via_client, got, rtol=1e-6)
    crows = client.pull("rag-0", "user", [[1, 2, 3], [9]])
    np.testing.assert_array_equal(crows, rows)


def test_rest_ragged_rejected_for_fixed_field_models(trained, tmp_path,
                                                     server):
    """A ragged payload against a model WITHOUT combiners (fixed field count
    is part of the architecture) stays the CALLER's 400 — padding it would
    fabricate zero rows into the tower and return wrong logits with a 200."""
    model, trainer, state, batch = trained
    base, _httpd = server
    path = str(tmp_path / "fixed_export")
    export_standalone(state, model, path, model_sign="fix-0")
    status, _ = _req(f"{base}/models", "POST",
                     {"model_sign": "fix-0", "model_uri": path})
    assert status == 200
    status, body = _req(f"{base}/models/fix-0/predict", "POST",
                        {"sparse": {"categorical": [[1, 2], [3]]},
                         "dense": np.asarray(batch["dense"])[:2].tolist()})
    assert status == 400 and "categorical" in body["error"]


def test_micro_batching_mixed_ragged_widths(tmp_path):
    """Concurrent ragged predicts of DIFFERENT widths through the
    MicroBatcher: the shape-keyed grouping isolates widths (a merged group
    would np.concatenate mismatched trailing dims and 500) and every client
    matches the unbatched oracle. All widths are DISTINCT on purpose: the
    two-tower scores in-batch, so merging same-width requests legitimately
    changes its (B, B) output — aggregation itself is pinned by
    test_predict_micro_batching on a per-row model."""
    import concurrent.futures

    from openembedding_tpu.models import make_two_tower

    model = make_two_tower(64, 64, dim=4, tower=(8,), combiner="mean",
                           compute_dtype=jnp.float32)
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=1)
    warm = {"sparse": {"user": jnp.asarray([[1, 2], [3, -1]]),
                       "item": jnp.asarray([[5, -1], [6, 7]])},
            "dense": None, "label": None}
    state = trainer.init(warm)
    state, _ = trainer.jit_train_step()(state, warm)
    path = str(tmp_path / "mw_export")
    export_standalone(state, model, path, model_sign="mw-0")
    srv = make_server(str(tmp_path / "mw_reg"), batch_window_ms=150.0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        status, _ = _req(f"{base}/models", "POST",
                         {"model_sign": "mw-0", "model_uri": path})
        assert status == 200

        # widths 1, 2 and 3 (ragged -> server buckets 1/2/4), fired together
        reqs = [
            {"sparse": {"user": [[1], [2]], "item": [[5], [6]]}},
            {"sparse": {"user": [[1, 2], [3]], "item": [[5], [6, 7]]}},
            {"sparse": {"user": [[1, 2, 3], [9]], "item": [[5], [6, 7, 8]]}},
        ]
        def one(r):
            status, out = _req(f"{base}/models/mw-0/predict", "POST", r)
            assert status == 200, out
            return np.asarray(out["logits"])

        with concurrent.futures.ThreadPoolExecutor(len(reqs)) as ex:
            outs = list(ex.map(one, reqs))
        # oracle pads with the server's OWN policy so it can never drift
        from openembedding_tpu.serving import _pad_ragged_bucketed
        sm = srv.manager.find_model("mw-0")
        for r, out in zip(reqs, outs):
            want = np.asarray(sm.predict(
                {"sparse": {k: _pad_ragged_bucketed(v)
                            for k, v in r["sparse"].items()}}))
            np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)
    finally:
        srv.shutdown()
