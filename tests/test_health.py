"""Round-16 training-health E2E (`ISSUE 12` acceptance): the in-jit numerics
sentinel (clean run -> health.* gauges populated, nonfinite_total == 0;
planted NaN -> `NonFiniteError` naming the table + the `health/nonfinite`
flight-recorder event + the numerics SLO flipping to BREACHED on a live
`GET /sloz`), the sampled step-time watch (`trainer.step_ms`, HLO-byte
attribution, `exchange.cost_drift`), sentinel-off stat hygiene, the mesh
additive-stats path, and the PeriodicReporter JSONL sink."""

import json
import threading
import urllib.request

import numpy as np
import pytest

import openembedding_tpu as oe
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.utils import metrics, slo, trace


@pytest.fixture(autouse=True)
def _fresh():
    metrics._REGISTRY.clear()
    trace.RECORDER.clear()
    yield
    metrics._REGISTRY.clear()
    trace.RECORDER.clear()


def _make(vocab=64, **kw):
    model = make_deepfm(vocabulary=vocab, dim=4, hidden=(8,))
    trainer = Trainer(model, oe.Adagrad(learning_rate=0.05), **kw)
    batch = next(iter(synthetic_criteo(8, id_space=vocab, steps=1, seed=0)))
    state = trainer.init(batch)
    return trainer, state, batch


# -- clean run: gauges populated, step_ms measured, SLOs OK -------------------


def test_clean_run_health_gauges_step_ms_and_numerics_ok():
    trainer, state, batch = _make(sentinel=True, measure_every=1)
    step = trainer.jit_train_step()
    for _ in range(3):
        state, mets = step(state, batch)
        health = trainer.record_step_stats(mets)
    (name,) = trainer.model.ps_specs().keys()
    assert health["sentinel"] is True
    assert health["nonfinite"] == {}
    for src in (name, "dense"):
        assert np.isfinite(health["grad_norm"][src])
        assert health["grad_norm"][src] > 0.0
    # the gauges the /metrics surface serves
    assert metrics.Accumulator.get(
        "health.grad_norm", "gauge", labels={"table": name}).value() > 0.0
    assert metrics.Accumulator.get("health.dense_grad_norm",
                                   "gauge").value() > 0.0
    # observed (as zero) EVERY step, so the numerics SLO is judged, not
    # UNKNOWN, on a clean run
    nt = metrics.Accumulator.get("health.nonfinite_total")
    assert nt.count == 3 and nt.value() == 0.0
    # measure_every=1 brackets every call into the step-time histogram
    assert metrics.Accumulator.get("trainer.step_ms", "hist").count == 3
    ev = slo.SLOEvaluator([s for s in slo.DEFAULT_SLOS
                           if s.name == "numerics"])
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.OK


def test_sentinel_off_leaves_stats_and_registry_clean():
    trainer, state, batch = _make()  # sentinel defaults off
    assert trainer.sentinel is False
    state, mets = trainer.jit_train_step()(state, batch)
    assert not any("grad_sumsq" in k or k.startswith("health/")
                   for k in mets["stats"])
    health = trainer.record_step_stats(mets)
    assert health["sentinel"] is False and health["nonfinite"] == {}
    with metrics._LOCK:
        names = {a.name for a in metrics._REGISTRY.values()}
    assert not any(n.startswith("health.") for n in names)
    assert "trainer.step_ms" not in names  # measure_every defaults off


# -- planted non-finite: the acceptance E2E -----------------------------------


@pytest.fixture()
def sloz_server(tmp_path):
    """A serving node exposing /sloz, with the global evaluator pinned to
    the numerics SLO for the test (restored after)."""
    from openembedding_tpu.serving import make_server
    slo.configure([s for s in slo.DEFAULT_SLOS if s.name == "numerics"])
    srv = make_server(str(tmp_path / "reg"), port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    slo.configure(list(slo.DEFAULT_SLOS))


def test_nonfinite_grad_trips_error_event_and_sloz_breach(sloz_server):
    trainer, state, batch = _make(halt_on_nonfinite=True)
    assert trainer.sentinel is True  # halt implies the sentinel
    (name,) = trainer.model.ps_specs().keys()
    ts = state.tables[name]
    state = state.replace(tables={
        **state.tables,
        name: ts.replace(weights=ts.weights.at[:].set(np.nan))})
    state, mets = trainer.jit_train_step()(state, batch)
    with pytest.raises(oe.NonFiniteError) as ei:
        trainer.record_step_stats(mets)
    # the error names the offending table (and the loss it poisoned)
    assert name in str(ei.value) and "loss" in str(ei.value)
    assert ei.value.sources[name] > 0

    # the flight recorder kept the breadcrumb
    evs = [e for e in trace.RECORDER.tail()
           if e.group == "health" and e.name == "nonfinite"]
    assert len(evs) == 1 and evs[0].attrs[name] > 0

    # and the numerics SLO flips to BREACHED on the live node
    with urllib.request.urlopen(f"{sloz_server}/sloz") as resp:
        doc = json.loads(resp.read())
    (v,) = doc["verdicts"]
    assert v["name"] == "numerics" and v["verdict"] == slo.BREACHED
    assert doc["exit_code"] == 1
    with urllib.request.urlopen(f"{sloz_server}/sloz?format=text") as resp:
        assert b"BREACHED" in resp.read()
    with urllib.request.urlopen(f"{sloz_server}/statusz") as resp:
        assert b"-- SLOs (GET /sloz for JSON) --" in resp.read()


def test_halt_off_records_but_does_not_raise():
    trainer, state, batch = _make(sentinel=True)
    (name,) = trainer.model.ps_specs().keys()
    ts = state.tables[name]
    state = state.replace(tables={
        **state.tables,
        name: ts.replace(weights=ts.weights.at[:].set(np.inf))})
    state, mets = trainer.jit_train_step()(state, batch)
    health = trainer.record_step_stats(mets)  # no raise: observe-only mode
    assert health["nonfinite"]
    assert metrics.Accumulator.get("health.nonfinite_total").value() > 0


# -- mesh path: additive stats psum to global figures -------------------------


def test_mesh_sentinel_grad_norms_and_quant_err():
    import jax
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    model = make_deepfm(vocabulary=64, dim=4, hidden=(8,))
    trainer = MeshTrainer(model, oe.Adagrad(learning_rate=0.05),
                          mesh=make_mesh(), wire="int8", sentinel=True)
    batch = next(iter(synthetic_criteo(8, id_space=64, steps=1, seed=0)))
    state = trainer.init(batch)
    state, mets = trainer.jit_train_step(batch, state)(state, batch)
    health = trainer.record_step_stats(mets)
    (name,) = trainer.model.ps_specs().keys()
    assert health["nonfinite"] == {}
    assert np.isfinite(health["grad_norm"][name])
    assert np.isfinite(health["grad_norm"]["dense"])
    if len(jax.devices()) > 1:
        # int8 wire + a real exchange: the quantization-error gauge derives
        assert metrics.Accumulator.get(
            "health.quant_err_rel", "gauge",
            labels={"table": name}).value() >= 0.0


# -- step watch: sampling cadence, attribution, cost drift --------------------


def test_stepwatch_cadence_attribution_and_cost_drift():
    from openembedding_tpu.utils.stepwatch import StepWatch, collective_bytes

    hlo = "\n".join([
        "  %a2a = f32[8,16]{1,0} all-to-all(%x)",
        "  %ar = bf16[4]{0} all-reduce(%y)",
        "  %other = f32[2,2]{1,0} add(%x, %x)",
    ])
    assert collective_bytes(hlo) == {"all_to_all": 8 * 16 * 4,
                                     "all_reduce": 4 * 2}

    watch = StepWatch(every=2, wire_cost=lambda: {"bytes_per_step": 1024})
    wrapped = watch.wrap(lambda x: x)  # no .lower: extraction error path
    for i in range(8):
        assert wrapped(i) == i
    assert watch.calls == 8 and watch.samples == 4
    assert metrics.Accumulator.get("trainer.step_ms", "hist").count == 4
    # HLO extraction failed once, loudly, and sampling carried on
    assert metrics.Accumulator.get("trainer.hlo_extract_errors").value() == 1
    # baseline = first 3 samples; drift gauged from sample 1 on, finite
    drift = metrics.Accumulator.get("exchange.cost_drift", "gauge").value()
    assert np.isfinite(drift)
    assert metrics.Accumulator.get("exchange.us_per_byte",
                                   "gauge").value() > 0.0


def test_stepwatch_jit_attribution_populates_hlo_bytes():
    import jax
    import jax.numpy as jnp

    from openembedding_tpu.utils.stepwatch import StepWatch

    fn = jax.jit(lambda x: jnp.sum(x * 2.0))
    watch = StepWatch(every=1)
    wrapped = watch.wrap(fn)
    x = jnp.ones((4,))
    assert float(wrapped(x)) == 8.0
    # proxied attributes still reach the jit fn (recompile guards use this)
    assert hasattr(wrapped, "lower")
    assert watch.samples == 1
    # no collectives on one CPU device: attribution is empty but step_ms
    # still measured, and nothing errored
    assert metrics.Accumulator.get("trainer.step_ms", "hist").count == 1
    with metrics._LOCK:
        names = {a.name for a in metrics._REGISTRY.values()}
    assert "trainer.hlo_extract_errors" not in names


def test_stepwatch_rejects_bad_every():
    from openembedding_tpu.utils.stepwatch import StepWatch
    with pytest.raises(ValueError):
        StepWatch(every=0)


# -- PeriodicReporter JSONL sink ----------------------------------------------


def test_periodic_reporter_jsonl(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    metrics.observe("train.examples", 128.0)
    rep = metrics.PeriodicReporter(60.0, sink=lambda s: None,
                                   jsonl_path=path).start()
    rep.stop()  # final flush writes one record even before the first tick
    with open(path) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert len(lines) == 1
    assert lines[0]["ts"] > 0
    assert lines[0]["metrics"]["train.examples"] == 128.0
