"""Fleet-causality tests: cross-process trace stitching over two live nodes,
delta lineage hop decomposition under injected delays, skew-corrected fleet
timeline ordering on deliberately skewed fake clocks, the freshness-SLO
breach/recover soak end to end, and the capsule lineage round-trip."""

import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.export import export_standalone
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.persist import IncrementalPersister, PersistPolicy
from openembedding_tpu.serving import make_server
from openembedding_tpu.sync import SyncPublisher, SyncSubscriber, lineage
from openembedding_tpu.utils import metrics, trace

VOCAB = 512

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    metrics._REGISTRY.clear()
    trace.RECORDER.clear()
    lineage.BOOK.clear()
    yield
    metrics._REGISTRY.clear()
    trace.RECORDER.clear()
    lineage.BOOK.clear()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def serving_node(tmp_path):
    srv = make_server(str(tmp_path / "reg_srv"))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv
    for sub in srv.subscribers.values():
        sub.stop()
    srv.shutdown()


@pytest.fixture()
def publisher_node(tmp_path):
    srv = make_server(str(tmp_path / "reg_pub"))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv
    srv.shutdown()


def _req(url, method="GET", payload=None, headers=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else {}), dict(e.headers)


# -- trace context + cross-process stitching ----------------------------------


def test_trace_context_header_roundtrip():
    """TraceContext serializes to the X-OETPU-Trace header value and back,
    with and without a parent span; extract falls back to the bare
    request-id header for pre-upgrade callers."""
    ctx = trace.TraceContext("rid-1", f"{trace.PROCESS_ID}:abc123")
    back = trace.TraceContext.from_header(ctx.to_header())
    assert (back.trace_id, back.parent_span) == (ctx.trace_id,
                                                 ctx.parent_span)
    bare = trace.TraceContext.from_header("rid-2")
    assert bare.trace_id == "rid-2" and bare.parent_span is None
    legacy = trace.extract_context({trace.REQUEST_ID_HEADER: "rid-3"})
    assert legacy.trace_id == "rid-3" and legacy.parent_span is None
    assert trace.extract_context({}) is None

    with trace.request("rid-4"):
        with trace.span("sync", "caller") as sp:
            cur = trace.TraceContext.current()
            assert cur.trace_id == "rid-4"
            assert cur.parent_span == f"{trace.PROCESS_ID}:{sp.span_id}"
            hdrs = trace.inject_headers()
    assert hdrs[trace.REQUEST_ID_HEADER] == "rid-4"
    assert hdrs[trace.TRACE_HEADER] == cur.to_header()


def test_cross_process_stitching_over_live_node(serving_node, tmp_path,
                                                capsys):
    """A caller span's injected X-OETPU-Trace header makes the serving
    node's http span a REMOTE child of the caller: same trace id, the
    caller's qualified span uid recorded as remote_parent, and
    tools/trace_report --trace renders the stitched tree with the hop
    marked."""
    base, srv = serving_node
    with trace.request("stitch-1"):
        with trace.span("sync", "caller") as caller:
            req = urllib.request.Request(f"{base}/healthz",
                                         headers=trace.inject_headers())
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
                # the serving node adopted the caller's trace id as its rid
                assert resp.headers["X-OETPU-Request-Id"] == "stitch-1"

    # the http span closes (and records) just AFTER the response body is
    # written, so reading the recorder immediately can race it — poll briefly
    deadline = time.time() + 5.0
    while True:
        http = next((s for s in trace.RECORDER.spans()
                     if s.name == "http" and s.trace_id == "stitch-1"), None)
        if http is not None:
            break
        assert time.time() < deadline, trace.RECORDER.spans()
        time.sleep(0.01)
    assert http.remote_parent == f"{trace.PROCESS_ID}:{caller.span_id}"
    assert http.parent_id is None  # root locally, child across the wire

    path = str(tmp_path / "stitched.json")
    trace.dump_chrome(path)
    tr = _load_tool("trace_report")
    assert tr.main([path, "--trace", "stitch-1"]) == 0
    out = capsys.readouterr().out
    assert "sync.caller" in out and "serving.http" in out
    assert "<-remote" in out
    # the http line is indented under the caller line
    lines = out.splitlines()
    caller_i = next(i for i, l in enumerate(lines) if "sync.caller" in l)
    http_l = next(l for l in lines if "serving.http" in l)
    assert http_l.startswith("  ") and not lines[caller_i].startswith(" ")


# -- hop decomposition --------------------------------------------------------


def test_hop_decomposition_with_injected_fetch_delay(tmp_path, publisher_node,
                                                     serving_node):
    """An artificially slow delta-payload serve lands on the FETCH hop of
    the applied delta's lineage record (not apply/swap), the record carries
    every hop of the chain, and the first predict at the version closes it
    with a serve hop."""
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=4, seed=1))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    pub_url, pub_srv = publisher_node
    srv_url, srv = serving_node

    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])
        p.wait()
        export_dir = str(tmp_path / "export")
        export_standalone(state, model, export_dir, model_sign="lin-0")
        pub_srv.publishers["lin-0"] = SyncPublisher(root)
        srv.manager.load_model("lin-0", export_dir)

        sub = SyncSubscriber(srv.manager, "lin-0", pub_url)
        assert sub.poll() == 0 and sub.version == 1

        pub = pub_srv.publishers["lin-0"]
        orig = pub.delta_table

        def slow_table(*a, **kw):
            time.sleep(0.25)
            return orig(*a, **kw)

        pub.delta_table = slow_table
        state, _ = step(state, batches[1])
        p.maybe_persist(state, batch=batches[1])
        p.wait()
        assert sub.poll() == 1, sub.last_error

    st = sub.status()
    lh = st["last_hops"]
    assert lh is not None and lh["step"] == 2
    hops = lh["hops"]
    assert {"commit", "publish", "fetch", "apply", "swap"} <= set(hops)
    assert hops["fetch"] >= 200.0, hops  # the injected delay lands here
    assert hops["fetch"] > hops["apply"] and hops["fetch"] > hops["swap"]
    # end-to-end freshness covers at least the stalled fetch
    assert st["freshness_ms"] is not None and st["freshness_ms"] >= 200.0

    rec = lineage.BOOK.get("lin-0", 2)
    assert rec is not None
    for stamp in ("birth", "commit", "seen", "fetched", "applied", "swapped"):
        assert rec.get(stamp) is not None, (stamp, rec)
    # birth -> ... -> swapped is non-decreasing within one clock domain pair
    assert rec["seen"] <= rec["fetched"] <= rec["applied"] <= rec["swapped"]

    body = {"sparse": {"categorical": np.asarray(
        batches[0]["sparse"]["categorical"]).tolist()},
        "dense": np.asarray(batches[0]["dense"]).tolist()}
    status, _, _ = _req(f"{srv_url}/models/lin-0/predict", "POST", body)
    assert status == 200
    rec = lineage.BOOK.get("lin-0", 2)
    assert rec.get("first_serve") is not None
    assert rec["hops"].get("serve") is not None
    # idempotent: a second predict must not move first_serve
    first = rec["first_serve"]
    _req(f"{srv_url}/models/lin-0/predict", "POST", body)
    assert lineage.BOOK.get("lin-0", 2)["first_serve"] == first
    # the hop histogram carries the decomposition with the hop= label
    acc = metrics.Accumulator.get("sync.hop_ms", "hist",
                                  labels={"hop": "fetch"})
    assert acc.count >= 1 and acc.hist_snapshot()[4] >= 200.0


def test_note_clock_ewma():
    sub = SyncSubscriber(manager=None, model_sign="m", feed="http://feed")
    # Cristian: offset = server - (t0+t2)/2; first sample lands directly
    sub._note_clock(100.5, 99.9, 100.1)
    assert abs(sub._clock_offset_s - 0.5) < 1e-9
    # EWMA (alpha 0.3) moves toward a new estimate without jumping
    sub._note_clock(101.5, 99.9, 100.1)  # sample: +1.5
    assert 0.5 < sub._clock_offset_s < 1.5
    assert abs(sub._clock_offset_s - (0.5 + 0.3 * 1.0)) < 1e-9
    assert sub.status()["clock_offset_ms"] == sub._clock_offset_s * 1e3


# -- skew-corrected fleet timeline (pure merge over fake docs) ---------------


def test_fleet_timeline_merge_corrects_deliberate_skew():
    """Two fake nodes, one with a +5s clock: after per-node offset
    correction the merged timeline interleaves causally (the skewed node's
    event does NOT sort 5s late), and a lineage record's publisher-domain
    stamps translate through its own offset_s so the chain stays
    contiguous and non-decreasing."""
    ftl = _load_tool("fleet_timeline")
    t = 1_000_000.0
    skew = 5.0
    # node A's clock reads +5s: every stamp it reports is wall+5, its
    # probe-estimated offset to the scraper is -5
    doc_a = {"events": [
        {"group": "sync", "name": "a_first", "ts": t + 0.10 + skew},
        {"group": "sync", "name": "a_last", "ts": t + 0.40 + skew}],
        "spans": [], "lineage": []}
    # node B is in the scraper's domain; its subscriber estimated the
    # publisher (A) clock offset at +5 (offset_s), so birth/commit below are
    # publisher-domain stamps
    doc_b = {"events": [
        {"group": "sync", "name": "b_mid", "ts": t + 0.25}],
        "spans": [],
        "lineage": [{"sign": "m", "step": 7, "offset_s": skew,
                     "birth": t + 0.05 + skew, "commit": t + 0.12 + skew,
                     "seen": t + 0.20, "fetched": t + 0.28,
                     "applied": t + 0.30, "swapped": t + 0.31,
                     "first_serve": t + 0.33,
                     "hops": {"fetch": 80.0, "apply": 20.0}}]}
    items = ftl.merge([("A", doc_a, -skew), ("B", doc_b, 0.0)])
    whats = [it["what"] for it in items]
    # causal order, not raw-clock order: A's stamps came back by 5s
    assert whats.index("sync.a_first") < whats.index("sync.b_mid")
    assert whats.index("sync.b_mid") < whats.index("sync.a_last")
    chain = [it for it in items if it["kind"] == "DELTA"]
    labels = [it["what"].split()[1] for it in chain]
    assert labels == ["birth", "commit", "publish", "fetch", "apply",
                      "swap", "first_predict"]
    ts = [it["ts"] for it in chain]
    assert all(a <= b for a, b in zip(ts, ts[1:]))
    # corrected birth sits on the scraper axis (skew removed), before seen
    assert abs(chain[0]["ts"] - (t + 0.05)) < 1e-6
    # version filter keeps the chain, drops unrelated events
    only = ftl.filter_items(items, version=7)
    assert {it["kind"] for it in only} == {"DELTA"} and len(only) == 7
    assert "m#7 fetch (80.0ms)" in [it["what"] for it in only]


def test_fleet_timeline_causal_clamp():
    """Residual skew that would reorder a chain (fetch before publish) is
    clamped non-decreasing instead of rendering causal nonsense."""
    ftl = _load_tool("fleet_timeline")
    t = 2_000_000.0
    doc = {"events": [], "spans": [],
           "lineage": [{"sign": "m", "step": 3, "offset_s": -0.050,
                        # commit translates to t+0.060 local — AFTER seen
                        "commit": t + 0.010, "seen": t + 0.040,
                        "fetched": t + 0.045, "swapped": t + 0.047}]}
    items = ftl.merge([("n", doc, 0.0)])
    ts = [it["ts"] for it in items]
    assert all(a <= b for a, b in zip(ts, ts[1:]))
    labels = [it["what"].split()[1] for it in items]
    assert labels == ["commit", "publish", "fetch", "swap"]


# -- the acceptance scenario: stall -> BREACHED -> recover -> OK --------------


def test_freshness_slo_breach_and_recover_e2e(tmp_path):
    """tools/sync_soak.py with an injected publisher stall: the
    serving_freshness SLO flips to BREACHED while delta payloads are
    withheld, the stalled hop is attributed to `fetch` in sync.hop_ms, the
    SLO recovers to OK once a post-stall delta lands, and the merged
    /timelinez timeline renders the last delta's full chain contiguous and
    ordered."""
    from openembedding_tpu.utils import slo
    spec = importlib.util.spec_from_file_location(
        "sync_soak", os.path.join(REPO, "tools", "sync_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    try:
        report = soak.run(steps=20, persist_every=4, interval_s=0.05,
                          step_delay_s=0.3, stall_s=2.5,
                          stall_after_frac=0.25,
                          freshness_threshold_ms=1100.0, timeline=True,
                          workdir=str(tmp_path / "soak"), predict_threads=2,
                          quiet=True)
    finally:
        slo.configure(list(slo.DEFAULT_SLOS))
    assert report["freshness_breached"] is True
    assert report["freshness_recovered"] is True
    assert report["stalled_hop"] == "fetch", report["hop_max_ms"]
    assert report["hop_max_ms"]["fetch"] >= 1000.0, report["hop_max_ms"]
    assert report["slo"]["serving_freshness"] == "OK"  # recovered at exit
    assert report["timeline"]["chain_ok"] is True
    assert report["timeline"]["chain"] == [
        "birth", "commit", "publish", "fetch", "apply", "swap",
        "first_predict"]
    assert report["failed_predicts"] == 0


# -- capsules bundle lineage --------------------------------------------------


def test_capsule_lineage_roundtrip(tmp_path):
    from openembedding_tpu.utils import capsule
    lineage.BOOK.record("cap-0", 9, birth=1.0, swapped=2.0,
                        hops={"fetch": 40.0})
    capsule.configure(str(tmp_path / "caps"))
    try:
        path = capsule.trigger("lineage_test", origin="test_lineage")
    finally:
        capsule.configure(None)
    assert path and os.path.exists(path)
    doc = capsule.load(path)
    recs = doc["lineage"]
    assert any(r.get("sign") == "cap-0" and r.get("step") == 9
               and r.get("hops", {}).get("fetch") == 40.0 for r in recs)
