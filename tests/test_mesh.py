"""Multi-device mesh tests on the virtual 8-device CPU mesh.

The TPU-native version of the reference's simulated-cluster tests (`entry/c_api_test.h`:
fork-based multi-process cluster, deterministic `test` optimizer, host-side replica
asserting exact equality; SURVEY.md §4)."""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import openembedding_tpu as embed
from openembedding_tpu.embedding import EmbeddingSpec, EmbeddingTableState
from openembedding_tpu.parallel import (MeshTrainer, deinterleave_rows,
                                        interleave_rows, make_mesh,
                                        sharded_apply_gradients, sharded_lookup,
                                        sharded_lookup_train)

S = 8  # conftest forces 8 virtual CPU devices


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == S
    return make_mesh()


def shard_table(mesh, spec, opt, weights_id_major):
    """Build a sharded EmbeddingTableState from an id-major host array."""
    vocab, dim = weights_id_major.shape
    w = interleave_rows(jnp.asarray(weights_id_major), S)
    slots = opt.init_slots(w.shape[0], dim)
    state = EmbeddingTableState(weights=w, slots=slots, keys=None, overflow=None)
    from jax.sharding import NamedSharding
    shardings = EmbeddingTableState(
        weights=NamedSharding(mesh, P("data", None)),
        slots={k: NamedSharding(mesh, P("data", None)) for k in slots},
        keys=None, overflow=None)
    return jax.device_put(state, shardings)


def test_interleave_roundtrip():
    w = jnp.arange(20 * 3, dtype=jnp.float32).reshape(20, 3)
    inter = interleave_rows(w, 4)
    # shard-major layout: row (s*rps + r) holds id r*4+s; row 5 = shard 1 local 0 = id 1
    np.testing.assert_array_equal(np.asarray(inter[0]), np.asarray(w[0]))
    np.testing.assert_array_equal(np.asarray(inter[5]), np.asarray(w[1]))
    back = deinterleave_rows(inter, 4, 20)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(w))


def test_sharded_lookup_matches_gather(mesh):
    """Pull through the a2a protocol == plain jnp.take on the id-major table."""
    rng = np.random.default_rng(0)
    vocab, dim, B = 64, 4, 16 * S
    table = rng.normal(size=(vocab, dim)).astype(np.float32)
    spec = EmbeddingSpec(name="v", input_dim=vocab, output_dim=dim, variable_id=0)
    opt = embed.SGD(learning_rate=0.1)
    state = shard_table(mesh, spec, opt, table)
    ids = rng.integers(0, vocab, size=(B,))

    def f(state, ids):
        return sharded_lookup(spec, state, ids)

    out = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(EmbeddingTableState(weights=P("data", None),
                                      slots={"moment": P("data", None)},
                                      keys=None, overflow=None), P("data")),
        out_specs=P("data"), check_vma=False))(state, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), table[ids], rtol=1e-6)


def test_sharded_train_pull_and_update_selfcheck(mesh):
    """Reference-style self-checking workload: TestOptimizer + host replica, multiple
    rounds of pull/push/update with duplicate ids across devices, exact equality
    (`entry/c_api_test.h:32-182`)."""
    rng = np.random.default_rng(1)
    vocab, dim, per_dev = 48, 4, 12
    B = per_dev * S
    opt = embed.TestOptimizer(learning_rate=1.0, flip=100.0, init=0.0)
    spec = EmbeddingSpec(name="v", input_dim=vocab, output_dim=dim, variable_id=0)
    table0 = rng.normal(size=(vocab, dim)).astype(np.float32)
    state = shard_table(mesh, spec, opt, table0)

    # host replica
    host_w = table0.copy()
    host_flip = np.zeros((vocab, 1), np.float32)

    table_spec = EmbeddingTableState(
        weights=P("data", None), slots={"flip_state": P("data", None)},
        keys=None, overflow=None)

    def step(state, ids, grads):
        state, rows, stats, plan = sharded_lookup_train(spec, state, ids)
        state, push_stats = sharded_apply_gradients(spec, state, opt, ids, grads,
                                                    plan=plan)
        return state, rows, {**stats, **push_stats}

    jstep = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(table_spec, P("data"), P("data")),
        out_specs=(table_spec, P("data"), P()), check_vma=False))

    for round_i in range(4):
        ids = rng.integers(0, vocab, size=(B,))
        grads = rng.normal(size=(B, dim)).astype(np.float32)
        state, rows, stats = jstep(state, jnp.asarray(ids), jnp.asarray(grads))
        # pull must have returned pre-update weights
        np.testing.assert_allclose(np.asarray(rows), host_w[ids], rtol=1e-5,
                                   err_msg=f"round {round_i} pull")
        assert int(stats["v/pull_overflow"] if "v/pull_overflow" in stats
                   else stats["pull_overflow"]) == 0
        # host replica update: per unique id, summed grads / count + flip
        for uid in np.unique(ids):
            sel = ids == uid
            g = grads[sel].sum(axis=0)
            count = sel.sum()
            host_flip[uid] = 100.0 - host_flip[uid]
            host_w[uid] += 1.0 * g / count + host_flip[uid]

    final = deinterleave_rows(np.asarray(state.weights), S, vocab)
    np.testing.assert_allclose(np.asarray(final), host_w, rtol=1e-4, atol=1e-4)


def make_batch(rng, vocab, B, fields=3):
    ids = rng.integers(0, vocab, size=(B, fields))
    y = (ids.sum(axis=1) % 2).astype(np.float32)
    return {"sparse": {"emb": jnp.asarray(ids)}, "label": jnp.asarray(y)}


class TinyDense(nn.Module):
    @nn.compact
    def __call__(self, embedded, dense_inputs):
        parts = [embedded[k].reshape(embedded[k].shape[0], -1)
                 for k in sorted(embedded)]
        x = jnp.concatenate(parts, axis=-1)
        return nn.Dense(1)(x)[:, 0]


def test_mesh_trainer_end_to_end(mesh):
    """Full DP+sharded-table training on the mesh: loss decreases; stats flow."""
    rng = np.random.default_rng(0)
    vocab = 200
    layer = embed.Embedding(vocab, 8, name="emb")
    model = embed.EmbeddingModel(TinyDense(), [layer])
    trainer = MeshTrainer(model, optimizer=embed.Adagrad(learning_rate=0.05),
                          mesh=mesh)
    batch = make_batch(rng, vocab, 16 * S)
    state = trainer.init(batch)
    step = trainer.jit_train_step(batch, state)
    losses = []
    for _ in range(40):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses[::10]
    assert int(metrics["stats"]["emb/pull_indices"]) == 16 * S * 3
    ev = trainer.jit_eval_step(batch, state)(state, batch)
    assert np.isfinite(float(ev["loss"]))


def test_mesh_trainer_matches_single_device():
    """One-step exact equivalence of the Trainer composition: with identical initial
    dense params, the first step's embedding-row updates must be identical between
    the single-device Trainer and the MeshTrainer (the dense psum only diverges the
    dense params AFTER their own update, so step-0 row grads match exactly)."""
    rng = np.random.default_rng(3)
    vocab, dim, B = 32, 4, 8 * S
    ids = rng.integers(0, vocab, size=(B, 2))
    labels = rng.random(B).round().astype(np.float32)
    b = {"sparse": {"emb": jnp.asarray(ids)}, "label": jnp.asarray(labels)}

    def build(trainer_cls, loss_scale=1.0, **kw):
        layer = embed.Embedding(vocab, dim, name="emb",
                                embeddings_initializer=embed.Constant(0.1))
        model = embed.EmbeddingModel(
            TinyDense(), [layer],
            loss_fn=lambda lo, la: loss_scale * embed.model.binary_logloss(lo, la))
        return trainer_cls(model, optimizer=embed.Adagrad(learning_rate=0.1), **kw)

    # Mesh semantics (reference parity): each worker normalizes by its LOCAL batch and
    # grads are summed across workers — S x the global-mean gradient. The equivalent
    # single-device run scales its loss by S.
    tr1 = build(embed.Trainer, loss_scale=float(S))
    st1 = tr1.init(b)
    st1, m1 = jax.jit(tr1.train_step)(st1, b)

    tr2 = build(MeshTrainer, mesh=make_mesh())
    st2 = tr2.init(b)
    # same flax seed -> identical initial dense params (verify, then step)
    st2, m2 = tr2.jit_train_step(b, st2)(st2, b)

    w1 = np.asarray(st1.tables["emb"].weights)
    w2 = np.asarray(deinterleave_rows(st2.tables["emb"].weights, S, vocab))
    np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-6)
    a1 = np.asarray(st1.tables["emb"].slots["accum"])
    a2 = np.asarray(deinterleave_rows(st2.tables["emb"].slots["accum"], S, vocab))
    np.testing.assert_allclose(a2, a1, rtol=1e-5, atol=1e-6)
    # per-device loss pmean == global mean == (single-device scaled loss) / S
    np.testing.assert_allclose(float(m2["loss"]), float(m1["loss"]) / S, rtol=1e-5)


@pytest.mark.parametrize("seed,opt_name,dim,hashed,dup_heavy", [
    (11, "adam", 4, False, False),
    (12, "ftrl", 8, False, True),
    (13, "rmsprop", 4, True, False),
    (14, "adagrad", 8, True, True),
    (15, "momentum", 4, False, False),
    (16, "adamax", 4, True, True),
])
def test_mesh_matches_single_device_randomized(seed, opt_name, dim, hashed,
                                               dup_heavy):
    """Randomized breadth for the step-0 exchange parity: optimizer family ×
    row width × table kind × duplicate pressure, all seeded. Any mis-routed
    row, broken dedup-count, or optimizer-semantics drift in the sharded
    protocol shows up as a row mismatch against the single-device oracle."""
    opts = {"adam": lambda: embed.Adam(learning_rate=0.05),
            "ftrl": lambda: embed.Ftrl(learning_rate=0.1),
            "rmsprop": lambda: embed.RMSprop(learning_rate=0.05),
            "adagrad": lambda: embed.Adagrad(learning_rate=0.1),
            "momentum": lambda: embed.SGD(learning_rate=0.1, momentum=0.9),
            "adamax": lambda: embed.Adamax(learning_rate=0.05)}
    rng = np.random.default_rng(seed)
    vocab, B, F = 64, 8 * S, int(rng.integers(2, 5))
    id_pool = 6 if dup_heavy else vocab  # heavy duplicates stress counts
    ids = rng.integers(0, id_pool, size=(B, F))
    labels = rng.random(B).round().astype(np.float32)
    b = {"sparse": {"emb": jnp.asarray(ids)}, "label": jnp.asarray(labels)}

    def build(trainer_cls, loss_scale=1.0, **kw):
        layer = embed.Embedding(
            -1 if hashed else vocab, dim, name="emb",
            capacity=256 if hashed else 0,
            embeddings_initializer=embed.Constant(0.05))
        model = embed.EmbeddingModel(
            TinyDense(), [layer],
            loss_fn=lambda lo, la: loss_scale * embed.model.binary_logloss(
                lo, la))
        return trainer_cls(model, optimizer=opts[opt_name](), **kw)

    tr1 = build(embed.Trainer, loss_scale=float(S))
    st1 = tr1.init(b)
    st1, m1 = jax.jit(tr1.train_step)(st1, b)

    tr2 = build(MeshTrainer, mesh=make_mesh())
    st2 = tr2.init(b)
    st2, m2 = tr2.jit_train_step(b, st2)(st2, b)

    uniq = np.unique(ids.reshape(-1))
    r1 = np.asarray(tr1.table_lookup(
        tr1.model.specs["emb"], st1.tables["emb"], jnp.asarray(uniq)))

    from functools import partial
    from jax.sharding import PartitionSpec as P
    from openembedding_tpu.parallel.sharded import sharded_lookup
    spec2 = tr2.model.specs["emb"]
    pull = jax.jit(jax.shard_map(
        partial(sharded_lookup, spec2, axis=tr2.axis), mesh=tr2.mesh,
        in_specs=(tr2._table_pspec(spec2), P()), out_specs=P(),
        check_vma=False))
    ids2 = jnp.asarray(uniq)
    if st2.tables["emb"].keys is not None and st2.tables["emb"].keys.ndim == 2:
        from openembedding_tpu.ops.id64 import np_split_ids
        ids2 = jnp.asarray(np_split_ids(uniq.astype(np.int64)))
    r2 = np.asarray(pull(st2.tables["emb"], ids2))
    np.testing.assert_allclose(r2, r1, rtol=1e-5, atol=1e-6,
                               err_msg=f"{opt_name} dim{dim} hashed={hashed}")
    np.testing.assert_allclose(float(m2["loss"]), float(m1["loss"]) / S,
                               rtol=1e-5)


def test_mesh_hash_table_train(mesh):
    """Sharded hash-table variable trains end to end and surfaces overflow."""
    rng = np.random.default_rng(0)
    layer = embed.Embedding(-1, 8, name="emb", capacity=4096)
    model = embed.EmbeddingModel(TinyDense(), [layer])
    trainer = MeshTrainer(model, optimizer=embed.Adagrad(learning_rate=0.05),
                          mesh=mesh)
    # 63-bit-ish hashed ids
    ids = rng.integers(0, 2**62, size=(16 * S, 3), dtype=np.int64)
    batch = {"sparse": {"emb": jnp.asarray(ids)},
             "label": jnp.asarray((ids.sum(axis=1) % 2).astype(np.float32))}
    state = trainer.init(batch)
    step = trainer.jit_train_step(batch, state)
    losses = []
    for _ in range(30):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses[::10]
    assert int(state.tables["emb"].overflow) == 0
    inserted = int((np.asarray(state.tables["emb"].keys) >= 0).sum())
    expected_unique = len(np.unique(ids))
    assert inserted == expected_unique
