"""Statistical correctness at scale: held-out AUC against a KNOWN optimum.

The reference validates its benchmark models by AUC on real Criteo
(`test/benchmark/criteo_deepctr.py`, `documents/en/benchmark.md:41-56`); a test
battery cannot ship terabytes, so `data.planted_criteo` plants a deterministic
id-conditional signal and `data.planted_logit` IS the generative model's own
scorer — its held-out AUC is the Bayes-optimal target. Any model with a per-id
linear term (LR, W&D, DeepFM's first order) can represent the true scorer, so
after ~10^6 training rows its held-out AUC must land within tolerance of the
oracle's. This replaces eyeballing loss curves with a regression metric: a
sparse-path bug (dropped gradients, mis-routed rows, broken dedup) shows up as
an AUC gap long before it breaks shape checks."""

import numpy as np
import pytest

import jax

import openembedding_tpu as embed
from openembedding_tpu.data import planted_criteo, planted_logit
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm, make_lr, make_wdl
from openembedding_tpu.utils.metrics import auc

VOCAB = 1 << 15
BATCH = 512
STEPS_PER_EPOCH = 200
EPOCHS = 10  # ~1.02M training rows


@pytest.fixture(scope="module")
def heldout():
    batches = list(planted_criteo(BATCH, steps=20, seed=999))
    labels = np.concatenate([b["label"] for b in batches])
    true_logits = np.concatenate(
        [planted_logit(b["sparse"]["categorical"].astype(np.int64), seed=1)
         for b in batches])
    oracle = auc(labels, true_logits)
    # the planted signal itself must be strong and deterministic
    assert 0.82 < oracle < 0.84, oracle
    return batches, labels, oracle


def _train_and_score(model, heldout, epochs=EPOCHS):
    batches_h, labels, _ = heldout
    trainer = Trainer(model, embed.Adam(learning_rate=0.02))
    state = None
    many = trainer.jit_train_many()
    for epoch in range(epochs):
        batches = list(planted_criteo(BATCH, steps=STEPS_PER_EPOCH,
                                      seed=epoch))
        stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
        if state is None:
            state = trainer.init(batches[0])
        state, m = many(state, stacked)
    assert np.isfinite(np.asarray(m["loss"])).all()
    ev = trainer.jit_eval_step()
    scores = np.concatenate(
        [np.asarray(ev(state, b)["logits"]).reshape(-1) for b in batches_h])
    return auc(labels, scores)


# Tolerances are measured-margin + ~0.005 drift slack, not guesses (VERDICT r4
# weak #6 called the old uniform 0.03 loose). Every seed below is fixed, so on
# one platform the achieved AUC is deterministic; measured r5 on the CPU suite
# (oracle 0.8298): lr margin +0.0183, wdl +0.0196, deepfm +0.0308. The slack
# absorbs cross-version/XLA numeric drift (~1e-3), not regressions.
#
# The tight margins are PLATFORM-TUNED (ADVICE r5): they were measured on the
# CPU suite, and reduction order / bf16 matmul behavior differ enough on TPU
# (or any other backend) that the snug deepfm bound can trip without any real
# regression. `_margin` therefore gates the tight bound on the platform it
# was measured on and falls back to a platform-independent floor of >= 0.03
# margin (plus 0.01 cross-platform slack) everywhere else.


def _margin(cpu_tuned: float) -> float:
    if jax.default_backend() == "cpu":
        return cpu_tuned
    return max(cpu_tuned, 0.03) + 0.01


def test_lr_reaches_planted_optimum(heldout):
    _, _, oracle = heldout
    got = _train_and_score(make_lr(vocabulary=VOCAB), heldout)
    assert got > oracle - _margin(0.024), (got, oracle)


def test_wdl_reaches_planted_optimum(heldout):
    _, _, oracle = heldout
    got = _train_and_score(
        make_wdl(vocabulary=VOCAB, dim=8, hidden=(64, 32)), heldout)
    assert got > oracle - _margin(0.025), (got, oracle)


def test_deepfm_reaches_planted_optimum(heldout):
    _, _, oracle = heldout
    got = _train_and_score(
        make_deepfm(vocabulary=VOCAB, dim=8, hidden=(64, 32)), heldout)
    # the FM/deep tower takes longer to stop fighting the linear term;
    # measured 0.7990 vs oracle 0.8298 at 1M rows (r5) — margin 0.0308, so
    # 0.035 is already snug (4.2 millipoints of slack) on CPU
    assert got > oracle - _margin(0.035), (got, oracle)


def test_mesh_trainer_reaches_planted_optimum(heldout):
    """The sharded exchange protocol trains to the same statistical quality:
    8-device mesh, fused dedup+routing, all_to_all pull/push."""
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    batches_h, labels, oracle = heldout
    trainer = MeshTrainer(make_lr(vocabulary=VOCAB),
                          embed.Adam(learning_rate=0.02), mesh=make_mesh())
    state = None
    many = None
    for epoch in range(EPOCHS):
        batches = list(planted_criteo(BATCH, steps=STEPS_PER_EPOCH,
                                      seed=epoch))
        stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
        if state is None:
            state = trainer.init(batches[0])
            many = trainer.jit_train_many(stacked, state)
        state, m = many(state, stacked)
    assert np.isfinite(np.asarray(m["loss"])).all()
    ev = trainer.jit_eval_step(batches_h[0], state)
    scores = np.concatenate(
        [np.asarray(ev(state, b)["logits"]).reshape(-1) for b in batches_h])
    got = auc(labels, scores)
    # sharded LR trains the same model as test_lr (exchange parity is pinned
    # exactly elsewhere); same data-driven bound as the single-device case
    assert got > oracle - _margin(0.024), (got, oracle)


@pytest.mark.slow  # ~1 min of training; tier-1's timed window can't afford it
def test_mesh_trainer_int8_ef_wire_parity(heldout):
    """Round-13 acceptance: the int8 exchange wire with error feedback (on by
    default for int8 — `MeshTrainer.ef_for`) trains to AUC parity with the
    fp32 wire on the same data. A dim-8 WDL so the per-block quantizer does
    real damage for EF + stochastic rounding to repair (dim-1 LR rows survive
    int8 almost losslessly — sign x max-abs — and would prove nothing).
    Reduced epochs: parity is a DIFFERENCE of two runs on identical batches,
    so it needs far fewer rows than the absolute-AUC bounds above. Marked
    slow: the statistical int8 story is covered in-window by the cheap
    pinned tests in tests/test_wire_inband.py (EF convergence, SR bounds);
    this end-to-end AUC run rides the full (`-m ''`) battery."""
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    batches_h, labels, _ = heldout
    epochs = 4

    def run(wire):
        trainer = MeshTrainer(
            make_wdl(vocabulary=VOCAB, dim=8, hidden=(64, 32)),
            embed.Adam(learning_rate=0.02), mesh=make_mesh(), wire=wire)
        state = None
        many = None
        for epoch in range(epochs):
            batches = list(planted_criteo(BATCH, steps=STEPS_PER_EPOCH,
                                          seed=epoch))
            stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs),
                                             *batches)
            if state is None:
                state = trainer.init(batches[0])
                many = trainer.jit_train_many(stacked, state)
            state, m = many(state, stacked)
        assert np.isfinite(np.asarray(m["loss"])).all()
        if wire == "int8":  # EF attached and actually absorbing residuals
            assert all(ts.ef is not None for ts in state.tables.values())
        ev = trainer.jit_eval_step(batches_h[0], state)
        scores = np.concatenate(
            [np.asarray(ev(state, b)["logits"]).reshape(-1)
             for b in batches_h])
        return auc(labels, scores)

    a_fp32 = run("fp32")
    a_int8 = run("int8")
    # measured on the CPU suite: see the platform note above `_margin`
    assert abs(a_int8 - a_fp32) < _margin(0.01), (a_int8, a_fp32)


@pytest.mark.slow  # two ~1 min training runs; rides the full (`-m ''`) battery
def test_mesh_trainer_dense_wire_int8_parity(heldout):
    """Round-17 acceptance: quantizing the dense ZeRO collectives
    (`dense_wire="int8"`: in-band two-stage grad reduce + bf16-carrier param
    all_gather, per-chunk EF + fp32 masters) trains to AUC parity with the
    lossless round-14 path on the same data. Both runs also quantize the
    sparse exchange so the delta isolates the DENSE wire. Same reduced-epoch
    rationale as the sibling test above: parity is a difference of two runs
    on identical batches."""
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    batches_h, labels, _ = heldout
    epochs = 4

    def run(dense_wire):
        trainer = MeshTrainer(
            make_wdl(vocabulary=VOCAB, dim=8, hidden=(64, 32)),
            embed.Adam(learning_rate=0.02), mesh=make_mesh(), wire="int8",
            dense_shard=True, dense_wire=dense_wire)
        state = None
        many = None
        for epoch in range(epochs):
            batches = list(planted_criteo(BATCH, steps=STEPS_PER_EPOCH,
                                          seed=epoch))
            stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs),
                                             *batches)
            if state is None:
                state = trainer.init(batches[0])
                many = trainer.jit_train_many(stacked, state)
            state, m = many(state, stacked)
        assert np.isfinite(np.asarray(m["loss"])).all()
        ev = trainer.jit_eval_step(batches_h[0], state)
        scores = np.concatenate(
            [np.asarray(ev(state, b)["logits"]).reshape(-1)
             for b in batches_h])
        return auc(labels, scores)

    a_lossless = run(None)
    a_q = run("int8")
    # measured on the CPU suite: see the platform note above `_margin`
    assert abs(a_q - a_lossless) < _margin(0.01), (a_q, a_lossless)
