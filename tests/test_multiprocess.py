"""TRUE multi-process cluster tests: N OS processes, `jax.distributed` over
gloo, 2 CPU devices per process — the reference's forked-cluster strategy
(`core::MultiProcess`, `entry/c_api_test.h:195,285`) for the machinery that has
multi-host-only code paths:

- `multihost.global_batch` (`jax.make_array_from_process_local_data`),
- `parallel/checkpoint.py` per-process shard writes + cross-process load,
- `persist.AsyncPersister`'s done-marker commit protocol, including the
  crash case (a process dying mid-checkpoint must prevent COMMIT).

The in-process 8-virtual-device suite (`tests/conftest.py`) covers numerics;
these tests cover process boundaries, so they spawn real interpreters (slow:
each pays jax import + compile). The single-process ORACLE comparison runs in
the pytest process itself on its 8 virtual devices — same global devices, same
GSPMD partitioning, so the loss trajectories must agree."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

WORKER = os.path.join(os.path.dirname(__file__), "multiprocess_worker.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# jaxlib < 0.5 cannot run cross-process computations on the CPU backend at
# all (workers die with "Multiprocess computations aren't implemented on the
# CPU backend") — a runtime capability gap, not a repo defect. The in-process
# 8-virtual-device suite still covers the numerics; only the real process
# boundaries go untested on such runtimes.
pytestmark = pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="CPU backend of this jaxlib lacks multiprocess computations")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(scenario, n, tmp, timeout=420, expect_rc=0, expect_result=True):
    """Run n worker processes to completion; returns the result.json payload.
    `expect_rc=-9` for scenarios that end in a deliberate SIGKILL."""
    port = _free_port()
    env = dict(os.environ)
    # strip the axon sitecustomize (each spawn would otherwise race for the
    # real TPU claim) and any inherited device-count flags
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, scenario, str(pid), str(n), str(port), tmp],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in range(n)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == expect_rc, \
            f"worker {pid} rc={p.returncode}\n--- output ---\n{out[-4000:]}"
    if not expect_result:
        return None
    result_path = os.path.join(tmp, "result.json")
    assert os.path.exists(result_path), "process 0 never wrote its result"
    with open(result_path) as f:
        return json.load(f)


def _oracle_losses(steps=4, gb=32):
    """Same training run, single process, same 8 global devices."""
    sys.path.insert(0, os.path.dirname(WORKER))
    try:
        from multiprocess_worker import build_trainer, make_global_batch
    finally:
        sys.path.pop(0)
    import jax
    from openembedding_tpu.parallel import make_mesh, multihost

    mesh = make_mesh()
    trainer = build_trainer(mesh)
    batches = [multihost.global_batch(make_global_batch(s, gb), mesh)
               for s in range(steps)]
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    losses = []
    for b in batches:
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    return losses


def test_multiprocess_train_and_sharded_checkpoint(tmp_path):
    """4 processes x 2 devices: global-batch assembly, sharded training, and a
    cross-process save_sharded/load_sharded round trip (shard-exact); the loss
    trajectory must match the single-process oracle on the same 8 devices."""
    result = _spawn("train_ckpt", 4, str(tmp_path))
    assert result["ok"] and result["num_processes"] == 4
    assert result["num_devices"] == 8
    oracle = _oracle_losses()
    np.testing.assert_allclose(result["losses"], oracle, rtol=1e-5, atol=1e-6)


def test_multiprocess_persist_commit(tmp_path):
    """2 processes: both write shards + done markers, process 0 commits, and
    the committed persist restores."""
    result = _spawn("persist_ok", 2, str(tmp_path))
    assert result["ok"]
    assert os.path.exists(os.path.join(result["committed"], "COMMIT"))


def test_multiprocess_incremental_persist_sigkill_restore(tmp_path):
    """The reference persists per server node across the cluster
    (`EmbeddingDumpOperator.cpp:36-96`); here: 2 processes train on one mesh,
    each writes its own delta shard files (touched ids unioned across
    processes), every process is SIGKILLed, and FRESH processes restore
    base+deltas bit-exactly — with uncommitted crash junk in the root
    ignored."""
    _spawn("persist_incr_train", 2, str(tmp_path), expect_rc=-9,
           expect_result=False)
    persist_root = os.path.join(str(tmp_path), "persists")
    # the crash junk phase A planted is still there when phase B starts
    assert os.path.isdir(os.path.join(persist_root, "delta_000000000099"))
    result = _spawn("persist_incr_restore", 2, str(tmp_path))
    assert result["ok"] and result["shards_checked"] > 0


def test_multiprocess_incremental_persist_hash_table(tmp_path):
    """Same crash-and-restore story on the HASH-table (hashed 2^40-id) config:
    per-process delta shards carry id-keyed rows, replay re-inserts through
    the sharded find-or-insert kernel, and pulled rows for the touched-id
    union match bit-exactly (slot order may differ; values by id may not)."""
    _spawn("persist_incr_hash_train", 2, str(tmp_path), expect_rc=-9,
           expect_result=False)
    result = _spawn("persist_incr_hash_restore", 2, str(tmp_path))
    assert result["ok"] and result["rows_checked"] > 0


def test_multiprocess_persist_crash_blocks_commit(tmp_path):
    """2 processes: the second dies before writing anything; the commit wait
    must time out (surfaced to the caller) and NO COMMIT marker may exist —
    a restore can never see the partial dump."""
    result = _spawn("persist_kill", 2, str(tmp_path))
    assert result["ok"]
    assert "finished writing" in result["error_surfaced"]
    persist_root = os.path.join(str(tmp_path), "persists")
    if os.path.isdir(persist_root):
        for name in os.listdir(persist_root):
            assert not os.path.exists(
                os.path.join(persist_root, name, "COMMIT"))
