"""Regression tests for review findings on the round-1 core (bf16 slots, negative-id
hash corruption, facade lazy-insert, overflow accounting, OOB lookup skew)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.embedding import (EmbeddingSpec, apply_gradients,
                                         init_table_state, lookup, lookup_train)


def test_bf16_table_adam_still_updates():
    """Adam's per-row beta_2^t must not round to 1.0 for bf16 tables (slots stay f32)."""
    opt = embed.Adam(learning_rate=0.1)
    spec = EmbeddingSpec(name="b", input_dim=32, output_dim=8, datatype="bfloat16",
                         initializer=embed.Constant(1.0), variable_id=0)
    state = init_table_state(spec, opt)
    assert state.slots["beta_2_t"].dtype == jnp.float32
    ids = jnp.asarray([1, 2, 3])
    grads = jnp.ones((3, 8), jnp.bfloat16)
    state = apply_gradients(spec, state, opt, ids, grads)
    w = np.asarray(state.weights.astype(jnp.float32))
    assert not np.allclose(w[1], 1.0), "bf16 row did not move"
    b2t = float(np.asarray(state.slots["beta_2_t"]).min())
    assert b2t < 1.0  # touched rows advanced to 0.999 exactly


def test_negative_ids_do_not_corrupt_hash_table():
    """-1 padding ids must neither claim nor update EMPTY slots."""
    opt = embed.SGD(learning_rate=1.0)
    spec = EmbeddingSpec(name="h", input_dim=-1, output_dim=4, capacity=64,
                         initializer=embed.Constant(0.0), variable_id=0)
    state = init_table_state(spec, opt)
    ids = jnp.asarray([-1, 7, -1], jnp.int64)
    state, rows = lookup_train(spec, state, ids)
    assert int((np.asarray(state.keys) >= 0).sum()) == 1  # only id 7 inserted
    np.testing.assert_array_equal(np.asarray(rows[0]), 0)
    grads = jnp.ones((3, 4), jnp.float32)
    state = apply_gradients(spec, state, opt, ids, grads)
    keys = np.asarray(state.keys)
    w = np.asarray(state.weights)
    # every slot whose key is still EMPTY must be untouched (weights stayed 0)
    np.testing.assert_array_equal(w[keys == -1], 0.0)
    # id 7's row got exactly its own gradient applied once
    np.testing.assert_allclose(w[keys == 7], -1.0, rtol=1e-6)


def test_embedding_variable_hash_table_trains():
    """The facade's training pull must insert ids (was: read-only lookup dropped
    every gradient)."""
    var = embed.EmbeddingVariable(
        EmbeddingSpec(name="h", input_dim=-1, output_dim=4, capacity=128,
                      initializer=embed.Constant(1.0), variable_id=0),
        optimizer=embed.SGD(learning_rate=1.0))
    rows = var.sparse_read(jnp.asarray([3, 5], jnp.int64))
    np.testing.assert_allclose(np.asarray(rows), 1.0)  # initializer value, not zeros
    var.push_gradients(jnp.asarray([3, 5], jnp.int64), jnp.ones((2, 4), jnp.float32))
    var.update_weights()
    after = np.asarray(var.read_only_pull(jnp.asarray([3, 5, 9], jnp.int64)))
    np.testing.assert_allclose(after[:2], 0.0, atol=1e-6)  # 1 - 1.0*1
    np.testing.assert_allclose(after[2], 0.0)  # 9 never inserted -> zeros


def test_hash_overflow_is_surfaced():
    opt = embed.SGD(learning_rate=0.1)
    spec = EmbeddingSpec(name="h", input_dim=-1, output_dim=2, capacity=4,
                         variable_id=0)
    state = init_table_state(spec, opt)
    ids = jnp.asarray(np.arange(10), jnp.int64)
    state, _ = lookup_train(spec, state, ids)
    assert int(state.overflow) == 6  # 4 fit, 6 overflowed
    state, _ = lookup_train(spec, state, ids)
    assert int(state.overflow) == 12  # cumulative


def test_out_of_range_lookup_returns_zeros():
    """Array-table lookup of id >= input_dim returns zeros (not the last row), matching
    the gradient path which drops those ids."""
    opt = embed.SGD(learning_rate=0.1)
    spec = EmbeddingSpec(name="a", input_dim=8, output_dim=4,
                         initializer=embed.Constant(2.0), variable_id=0)
    state = init_table_state(spec, opt)
    rows = np.asarray(lookup(spec, state, jnp.asarray([7, 8, 100, -3])))
    np.testing.assert_allclose(rows[0], 2.0)
    np.testing.assert_allclose(rows[1:], 0.0)


def test_sad_with_per_variable_optimizer_rejected():
    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, embedded, dense):
            return jnp.zeros((1,))

    with pytest.raises(ValueError, match="sparse_as_dense"):
        embed.EmbeddingModel(M(), [
            embed.Embedding(10, 4, name="x", sparse_as_dense=True,
                            optimizer=embed.SGD(learning_rate=0.0))])
