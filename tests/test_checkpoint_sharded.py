"""Sharded streaming checkpoint (`parallel/checkpoint.py`): per-shard files,
bounded host memory, reshard-on-load at any mesh size, async persist interop.

Reference parity targets: per-shard dump streams
(`server/EmbeddingDumpOperator.cpp:36-96`), coordinated per-node load
(`client/Model.cpp:89-134`), topology-change restore (np=2 -> np=8 e2e sweep,
`build.sh:91-150`), batched key re-insertion (`EmbeddingLoadOperator.cpp:58-111`).
"""

import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.parallel import (MeshTrainer, load_sharded, make_mesh,
                                        save_sharded, snapshot_addressable,
                                        checkpoint_layout)

S = 8


class TinyDense(nn.Module):
    @nn.compact
    def __call__(self, embedded, dense_inputs):
        parts = [embedded[k].reshape(embedded[k].shape[0], -1)
                 for k in sorted(embedded)]
        x = jnp.concatenate(parts, axis=-1)
        return nn.Dense(1)(x)[:, 0]


def make_batch(rng, vocab, B, hash_ids=False):
    if hash_ids:
        ids = rng.integers(0, 2**61, size=(B, 3), dtype=np.int64)
    else:
        ids = rng.integers(0, vocab, size=(B, 3))
    y = (ids.sum(axis=1) % 2).astype(np.float32)
    return {"sparse": {"emb": jnp.asarray(ids)}, "label": jnp.asarray(y)}


def build(vocab, trainer_cls, capacity=0, **kw):
    layer = embed.Embedding(vocab, 8, name="emb", capacity=capacity)
    model = embed.EmbeddingModel(TinyDense(), [layer])
    return embed.Trainer(model, optimizer=embed.Adagrad(learning_rate=0.05)) \
        if trainer_cls is embed.Trainer else \
        trainer_cls(model, optimizer=embed.Adagrad(learning_rate=0.05), **kw)


def train_some(trainer, batch, steps=6, mesh=True):
    state = trainer.init(batch)
    step = (trainer.jit_train_step(batch, state) if mesh
            else trainer.jit_train_step())
    for _ in range(steps):
        state, m = step(state, batch)
    return state, m


def all_rows(trainer, state, ids):
    """id-major rows via the trainer's own lookup path."""
    spec = trainer.model.specs["emb"]
    if isinstance(trainer, MeshTrainer):
        eval_fn = trainer.jit_eval_step  # noqa: F841 (compiled elsewhere)
        # use the sharded read-only pull through a tiny jit
        from jax.sharding import NamedSharding, PartitionSpec as P

        def pull(st, i):
            return trainer.table_lookup(spec, st.tables["emb"], i)

        shard = jax.shard_map(
            pull, mesh=trainer.mesh,
            in_specs=(trainer._state_pspec_tree(state),
                      P(trainer.mesh.axis_names[0])),
            out_specs=P(trainer.mesh.axis_names[0]),
            check_vma=False)
        return np.asarray(jax.jit(shard)(state, jnp.asarray(ids)))
    from openembedding_tpu.embedding import lookup
    return np.asarray(lookup(spec, state.tables["emb"], jnp.asarray(ids)))


# ---------------------------------------------------------------------------
# array tables
# ---------------------------------------------------------------------------


def test_sharded_roundtrip_array_same_mesh(tmp_path):
    rng = np.random.default_rng(0)
    vocab = 201  # not divisible by 8: padding rows in play
    mesh = make_mesh()
    tr = build(vocab, MeshTrainer, mesh=mesh)
    batch = make_batch(rng, vocab, 16 * S)
    state, _ = train_some(tr, batch)

    stats = {}
    save_sharded(state, tr.model, str(tmp_path), num_shards=S,
                 chunk_rows=7, _stats=stats)
    assert checkpoint_layout(str(tmp_path)) == "sharded"
    # per-shard files on disk, not one big table
    vdir = tmp_path / "variable_0"
    shard_dirs = sorted(os.listdir(vdir))
    assert len(shard_dirs) == S and shard_dirs[0] == "shard_00000_of_00008"
    # bounded host memory: no chunk bigger than chunk_rows ever materialized
    assert 0 < stats["max_host_rows"] <= 7

    tr2 = build(vocab, MeshTrainer, mesh=mesh)
    state2 = tr2.init(batch)
    restored = load_sharded(state2, tr2.model, str(tmp_path), num_shards=S)
    ids = np.arange(vocab)
    np.testing.assert_array_equal(all_rows(tr, state, np.tile(ids, 2)[:208]),
                                  all_rows(tr2, restored,
                                           np.tile(ids, 2)[:208]))
    # optimizer slots restored exactly: one more identical step stays identical
    s1, m1 = tr.jit_train_step(batch, state)(state, batch)
    s2, m2 = tr2.jit_train_step(batch, restored)(restored, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)


def test_sharded_mesh_to_single_and_back(tmp_path):
    """8-way sharded dump -> single-device restore -> single-file dump -> 4-way
    mesh restore: every row identical at every hop."""
    rng = np.random.default_rng(1)
    vocab = 97
    mesh = make_mesh()
    tr8 = build(vocab, MeshTrainer, mesh=mesh)
    batch = make_batch(rng, vocab, 16 * S)
    state8, _ = train_some(tr8, batch)
    tr8.save(state8, str(tmp_path / "c8"))  # MeshTrainer.save = sharded
    assert checkpoint_layout(str(tmp_path / "c8")) == "sharded"

    tr1 = build(vocab, embed.Trainer)
    state1 = tr1.init(batch)
    restored1 = tr1.load(state1, str(tmp_path / "c8"))  # dispatches on layout
    ids = np.arange(vocab)
    want = all_rows(tr8, state8, np.tile(ids, 2)[:104])
    np.testing.assert_array_equal(want, all_rows(tr1, restored1,
                                                 np.tile(ids, 2)[:104]))

    # sharded checkpoint restored at a DIFFERENT mesh size (8 -> 4)
    mesh4 = make_mesh(jax.devices("cpu")[:4])
    tr4 = build(vocab, MeshTrainer, mesh=mesh4)
    batch4 = make_batch(rng, vocab, 16 * 4)
    state4 = tr4.init(batch4)
    restored4 = tr4.load(state4, str(tmp_path / "c8"))
    np.testing.assert_array_equal(want[:100],
                                  all_rows(tr4, restored4,
                                           np.tile(ids, 2)[:100]))


# ---------------------------------------------------------------------------
# hash tables
# ---------------------------------------------------------------------------


def test_sharded_hash_topology_change(tmp_path):
    rng = np.random.default_rng(2)
    mesh = make_mesh()
    tr8 = build(-1, MeshTrainer, capacity=2048, mesh=mesh)
    batch = make_batch(rng, -1, 16 * S, hash_ids=True)
    state8, _ = train_some(tr8, batch)
    trained_ids = np.unique(np.asarray(batch["sparse"]["emb"]).reshape(-1))

    stats = {}
    save_sharded(state8, tr8.model, str(tmp_path), num_shards=S,
                 chunk_rows=13, _stats=stats)
    assert stats["max_host_rows"] <= 13
    # compacted per-shard ids are id-sorted
    ids0 = np.load(tmp_path / "variable_0" / "shard_00000_of_00008" / "ids.npy")
    assert (np.diff(ids0) > 0).all()
    # every shard's ids belong to it (id % S == shard)
    assert (ids0 % S == 0).all()

    # restore at 4-way mesh
    mesh4 = make_mesh(jax.devices("cpu")[:4])
    tr4 = build(-1, MeshTrainer, capacity=2048, mesh=mesh4)
    batch4 = make_batch(rng, -1, 16 * 4, hash_ids=True)
    state4 = tr4.init(batch4)
    restored4 = tr4.load(state4, str(tmp_path))
    pad = -(len(trained_ids) % -8)
    probe = np.concatenate([trained_ids, trained_ids[:pad]])
    np.testing.assert_array_equal(all_rows(tr8, state8, probe),
                                  all_rows(tr4, restored4, probe))

    # and into a single-device trainer
    tr1 = build(-1, embed.Trainer, capacity=2048)
    restored1 = tr1.load(tr1.init(batch), str(tmp_path))
    np.testing.assert_array_equal(all_rows(tr8, state8, probe),
                                  all_rows(tr1, restored1, probe))


def test_overflow_counter_is_per_variable(tmp_path):
    """A table that drops rows on restore (capacity pressure) must not leak its
    drop count into other tables' overflow counters."""
    rng = np.random.default_rng(7)
    mesh = make_mesh()
    # A: capacity so tight that a sharded restore must drop rows; B: roomy
    la = embed.Embedding(-1, 4, name="a", capacity=64)
    lb = embed.Embedding(-1, 4, name="b", capacity=4096)
    model = embed.EmbeddingModel(TinyDense(), [la, lb])
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1), mesh=mesh)
    ids_a = rng.integers(0, 2**61, size=(16 * S, 2), dtype=np.int64)
    ids_b = rng.integers(0, 2**61, size=(16 * S, 2), dtype=np.int64)
    batch = {"sparse": {"a": jnp.asarray(ids_a), "b": jnp.asarray(ids_b)},
             "label": jnp.asarray((ids_a.sum(1) % 2).astype(np.float32))}
    state = tr.init(batch)
    step = tr.jit_train_step(batch, state)
    for _ in range(4):
        state, _ = step(state, batch)
    save_sharded(state, model, str(tmp_path), num_shards=S)

    # restore table A into HALF the capacity: ~32 resident rows cannot fit in
    # 32 slots minus probe collisions, so the restore must drop some
    la2 = embed.Embedding(-1, 4, name="a", capacity=32)
    lb2 = embed.Embedding(-1, 4, name="b", capacity=4096)
    model2 = embed.EmbeddingModel(TinyDense(), [la2, lb2])
    tr2 = MeshTrainer(model2, embed.Adagrad(learning_rate=0.1), mesh=mesh)
    restored = load_sharded(tr2.init(batch), model2, str(tmp_path),
                            num_shards=S)
    a_over = int(np.asarray(restored.tables["a"].overflow))
    b_over = int(np.asarray(restored.tables["b"].overflow))
    assert a_over > 0  # the shrunken table really dropped rows
    assert b_over == 0  # ...and did not contaminate the roomy one


def test_np_hash_insert_vectorized_matches_sequential():
    """The vectorized host insert must be a valid open-addressing placement with
    the device kernel's probe sequence: every id findable, first-come slot wins."""
    from openembedding_tpu.tables.hash_table import np_hash_insert, np_mix

    def sequential(keys, ids, num_shards, num_probes=64):
        cps = keys.shape[0] // num_shards
        out = np.full(len(ids), -1, np.int64)
        base = (np_mix(ids) % np.uint64(cps)).astype(np.int64)
        for i in range(len(ids)):
            start = int(ids[i] % num_shards) * cps
            for d in range(min(num_probes, cps)):
                p = start + (int(base[i]) + d) % cps
                if keys[p] == -1:
                    keys[p] = ids[i]
                    out[i] = p
                    break
        return out

    rng = np.random.default_rng(3)
    for S_, cap, n in [(1, 64, 40), (4, 256, 150), (8, 64, 70)]:
        ids = np.unique(rng.integers(0, 2**61, size=n, dtype=np.int64))
        kv = np.full((cap,), -1, np.int64)
        ks = kv.copy()
        pv = np_hash_insert(kv, ids, S_)
        ps = sequential(ks, ids, S_)
        # Same per-shard fill: when the probe path covers the shard (cases
        # chosen so min(64, cps) == cps), both strategies fill each shard to
        # min(#owned, cps); under overload WHICH ids drop may differ (placement
        # races resolve in a different order), but never HOW MANY.
        cps = cap // S_
        for sh in range(S_):
            assert ((kv[sh * cps:(sh + 1) * cps] >= 0).sum()
                    == (ks[sh * cps:(sh + 1) * cps] >= 0).sum())
        assert (pv >= 0).sum() == (ps >= 0).sum()
        if (ps >= 0).all():  # no drops: identical resident sets
            np.testing.assert_array_equal(np.sort(kv), np.sort(ks))
        # findability: every placed id sits in its owner's range on its own
        # probe path with no EMPTY slot before it
        cps = cap // S_
        for i in np.nonzero(pv >= 0)[0]:
            start = int(ids[i] % S_) * cps
            base = int((np_mix(ids[i:i+1]) % np.uint64(cps))[0])
            d = 0
            while True:
                p = start + (base + d) % cps
                assert kv[p] != -1, "EMPTY slot on probe path before the id"
                if kv[p] == ids[i]:
                    break
                d += 1
                assert d < cps


# ---------------------------------------------------------------------------
# async persist through the sharded path
# ---------------------------------------------------------------------------


def test_persist_sharded_roundtrip(tmp_path):
    from openembedding_tpu.persist import AsyncPersister, PersistPolicy

    rng = np.random.default_rng(4)
    vocab = 120
    mesh = make_mesh()
    tr = build(vocab, MeshTrainer, mesh=mesh)
    batch = make_batch(rng, vocab, 16 * S)
    state = tr.init(batch)
    step = tr.jit_train_step(batch, state)

    with AsyncPersister(tr, tr.model, str(tmp_path), window=2,
                        policy=PersistPolicy(every_steps=2)) as p:
        for _ in range(5):
            state, _ = step(state, batch)
            p.maybe_persist(state)
        p.wait()
        # snapshots are per-shard (layout "sharded" on disk)
        from openembedding_tpu.persist import latest_persist
        newest = latest_persist(str(tmp_path))
        assert newest is not None and checkpoint_layout(newest) == "sharded"
        rows_before = all_rows(tr, state, np.arange(120)[:120])

        tr2 = build(vocab, MeshTrainer, mesh=mesh)
        restored = p.restore(tr2.init(batch))
    # restored state equals the persisted step's state: retrain remaining steps
    assert int(restored.step) in (2, 4)
    assert np.isfinite(rows_before).all()
    # the newest persist was at step 4; stepping restored forward once works
    step2 = tr2.jit_train_step(batch, restored)
    restored, m = step2(restored, batch)
    assert np.isfinite(float(m["loss"]))


def test_snapshot_addressable_isolated_from_donation(tmp_path):
    """The host snapshot must be a COPY: donating the state to the next step
    right after snapshotting must not corrupt the pending write."""
    rng = np.random.default_rng(5)
    mesh = make_mesh()
    tr = build(64, MeshTrainer, mesh=mesh)
    batch = make_batch(rng, 64, 16 * S)
    state, _ = train_some(tr, batch, steps=2)
    snap = snapshot_addressable(state, S)
    rows_before = all_rows(tr, state, np.arange(64))
    step = tr.jit_train_step(batch, state)
    state, _ = step(state, batch)  # donates the snapshotted state's buffers
    save_sharded(snap, tr.model, str(tmp_path), num_shards=S)
    tr2 = build(64, MeshTrainer, mesh=mesh)
    restored = load_sharded(tr2.init(batch), tr2.model, str(tmp_path),
                            num_shards=S)
    np.testing.assert_array_equal(rows_before, all_rows(tr2, restored,
                                                        np.arange(64)))
