"""Test harness: simulate an 8-device TPU mesh on CPU.

Mirrors the reference's test strategy of simulating a multi-process cluster inside one
test binary (`core::MultiProcess` fork harness, `entry/c_api_test.h:195,285`): here one
process hosts 8 virtual XLA CPU devices and shard_map/pjit run real collectives over
them (SURVEY.md §4 implication (a)).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
# The suite's parity tests assert EXACT (1e-5-ish) mesh-vs-single-device
# agreement, so the suite baseline pins the lossless wire format; the bf16
# production default and int8 are covered explicitly in tests/test_wire.py
# (which passes wire=... to MeshTrainer, overriding this env default).
os.environ.setdefault("OETPU_WIRE", "fp32")

import jax

# 63-bit hashed id spaces need int64 ids (`meta.HASH_VOCABULARY_THRESHOLD`)
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    # tier-1 (ROADMAP.md) runs `-m 'not slow'` under a hard wall-clock
    # timeout; multi-epoch training runs that have a cheaper pinned-parity
    # counterpart elsewhere opt out of that window with this marker.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 timed window")
