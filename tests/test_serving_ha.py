"""Serving HA: replica failover, kill/restart, and live-replica restore.

The reference's HA story (`entry/c_api_ha_test.cpp`: forked real server
processes, kill -9 loops while pulls run, restore via replica copy or
reload; `server/EmbeddingRestoreOperator.cpp`) maps here to:

- N REST serving processes sharing a file registry = N replicas; a client
  fails over by retrying the next node (the reference's `pick_one_replica`
  + `Status::NoReplica` retry lives client-side there too).
- A dead node restarts and lazily reloads from the registry.
- A NEW node with no shared filesystem rebuilds the model from a live peer
  via `restore_from_peer` (`:exportmeta`/`:rows`/`:dense` paged endpoints) —
  the reference's coordinated replica-iteration restore.

The in-process test covers the restore protocol end to end; the subprocess
test covers real process death (SIGKILL) and restart.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.export import StandaloneModel, export_standalone
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.serving import (ServingClient, make_server,
                                        restore_from_peer)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIGN = "ha-model-1"


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """A small trained DeepFM standalone export + a probe batch."""
    model = make_deepfm(vocabulary=512, dim=8)
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.1))
    batches = list(synthetic_criteo(32, id_space=512, steps=3, seed=3))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    for b in batches:
        state, _ = step(state, b)
    path = str(tmp_path_factory.mktemp("ha") / "export")
    export_standalone(state, model, path, model_sign=SIGN)
    return path, batches[0]


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _pull_failover(nodes, sign, variable, ids):
    """Replica failover through the shipped client (reference
    `pick_one_replica` + NoReplica-retry semantics, client-side)."""
    return {"weights": ServingClient(nodes).pull(sign, variable, ids).tolist()}


# ---------------------------------------------------------------------------
# in-process: restore protocol end to end
# ---------------------------------------------------------------------------


def test_restore_from_peer_roundtrip(exported, tmp_path):
    path, batch = exported
    reg1 = str(tmp_path / "reg1")
    srv = make_server(reg1)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        peer = f"http://127.0.0.1:{srv.server_address[1]}"
        _http("POST", f"{peer}/models", {"model_sign": SIGN, "model_uri": path})

        ids = [[1, 2], [3, 509]]
        base = _pull_failover([peer], SIGN, "categorical", ids)

        # page size 3 forces multi-page iteration over the hash rows
        dest = restore_from_peer(peer, SIGN, str(tmp_path / "restored"),
                                 page=3)
        restored = StandaloneModel.load(dest)
        got = np.asarray(restored.lookup("categorical", np.asarray(ids)))
        np.testing.assert_allclose(got, np.asarray(base["weights"]),
                                   rtol=0, atol=0)

        # full predict parity through the restored export
        orig = StandaloneModel.load(path)
        bp = {"sparse": {k: v.tolist() for k, v in batch["sparse"].items()},
              "dense": batch["dense"].tolist()}
        a = np.asarray(orig.predict({"sparse": batch["sparse"],
                                     "dense": batch["dense"]}))
        b = np.asarray(restored.predict({"sparse": batch["sparse"],
                                         "dense": batch["dense"]}))
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)

        # guardrails: bad ranges 400, unknown variable 404
        for q, code in ((f"{peer}/models/{SIGN}:rows?var=categorical&start=-1",
                         400),
                        (f"{peer}/models/{SIGN}:rows?var=nope", 404)):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(q, timeout=10)
            assert ei.value.code == code
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# subprocess: real kill -9 / restart (reference c_api_ha_test.cpp shape)
# ---------------------------------------------------------------------------


def _spawn_node(registry, timeout=120):
    env = dict(os.environ, PYTHONPATH=REPO, PYTHONUNBUFFERED="1",
               JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never contend for the real TPU
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "openembedding_tpu.serving",
         "--registry", registry, "--port", "0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    # read on a thread: a wedged child that stays alive without printing must
    # fail this test at `timeout`, not block readline() until the CI job dies
    import queue
    q = queue.Queue()

    def _reader():
        for line in proc.stdout:
            q.put(line)
        q.put(None)

    threading.Thread(target=_reader, daemon=True).start()
    deadline = time.monotonic() + timeout
    seen = []
    while time.monotonic() < deadline:
        try:
            line = q.get(timeout=max(0.1, deadline - time.monotonic()))
        except queue.Empty:
            break
        if line is None:
            break
        seen.append(line)
        if "serving on http://" in line:
            url = line.split("serving on ")[1].split()[0]
            return proc, url
    proc.kill()
    raise AssertionError(f"serving node never came up: {seen[-3:]!r}")


def test_ha_kill_restart_and_peer_restore(exported, tmp_path):
    path, _ = exported
    reg = str(tmp_path / "reg")
    os.makedirs(reg, exist_ok=True)
    ids = [[5, 6, 7]]
    procs = []
    try:
        n1, u1 = _spawn_node(reg)
        procs.append(n1)
        _http("POST", f"{u1}/models", {"model_sign": SIGN, "model_uri": path},
              timeout=120)
        base = _pull_failover([u1], SIGN, "categorical", ids)

        n2, u2 = _spawn_node(reg)
        procs.append(n2)
        # replica 2 serves the same answer from the shared registry
        r2 = _pull_failover([u2], SIGN, "categorical", ids)
        assert r2 == base

        # kill -9 replica 1 mid-service: the client fails over to replica 2
        n1.send_signal(signal.SIGKILL)
        n1.wait(timeout=30)
        r = _pull_failover([u1, u2], SIGN, "categorical", ids)
        assert r == base

        # a NEW node with NO shared filesystem restores from the live peer
        reg2 = str(tmp_path / "reg2")
        dest = restore_from_peer(u2, SIGN, str(tmp_path / "restored2"))
        n3, u3 = _spawn_node(reg2)
        procs.append(n3)
        _http("POST", f"{u3}/models", {"model_sign": SIGN, "model_uri": dest},
              timeout=120)
        r3 = _pull_failover([u3], SIGN, "categorical", ids)
        np.testing.assert_allclose(np.asarray(r3["weights"]),
                                   np.asarray(base["weights"]),
                                   rtol=0, atol=0)

        # the killed node restarts and serves again from the registry
        n1b, u1b = _spawn_node(reg)
        procs.append(n1b)
        r1b = _pull_failover([u1b], SIGN, "categorical", ids)
        assert r1b == base
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_restore_refuses_non_normal(exported, tmp_path):
    """restore_from_peer must refuse a model that isn't NORMAL (a CREATING/
    ERROR source would yield a partial or wrong artifact) and surface an
    unknown sign as the peer's 404."""
    import urllib.error

    path, _ = exported
    srv = make_server(str(tmp_path / "regnn"))
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        peer = f"http://127.0.0.1:{srv.server_address[1]}"
        # register as CREATING (never promoted): restore must refuse
        srv.manager.registry.create_model("half-0", path)
        with pytest.raises(RuntimeError, match="CREATING"):
            restore_from_peer(peer, "half-0", str(tmp_path / "d1"))
        with pytest.raises(urllib.error.HTTPError) as ei:
            restore_from_peer(peer, "nope-0", str(tmp_path / "d2"))
        assert ei.value.code == 404
    finally:
        srv.shutdown()
