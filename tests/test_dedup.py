"""Unit tests for the static-shape dedup / bucketing primitives (the counterparts of
the reference's client-side hot loops, `EmbeddingPullOperator.cpp:60-112`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from openembedding_tpu.ops.dedup import bucket_by_owner, unbucket, unique_with_counts


@pytest.mark.parametrize("n,vocab", [(16, 5), (128, 1000), (64, 2)])
def test_unique_with_counts_matches_numpy(n, vocab):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=n)
    res = jax.jit(unique_with_counts)(jnp.asarray(ids))
    expect_u, expect_c = np.unique(ids, return_counts=True)
    k = int(res.num_unique)
    assert k == len(expect_u)
    np.testing.assert_array_equal(np.asarray(res.unique_ids)[:k], expect_u)
    np.testing.assert_array_equal(np.asarray(res.counts)[:k], expect_c)
    # padding slots have count 0
    assert np.all(np.asarray(res.counts)[k:] == 0)
    # inverse maps each id back to its unique slot
    np.testing.assert_array_equal(np.asarray(res.unique_ids)[np.asarray(res.inverse)], ids)


def test_unique_single_value():
    ids = jnp.full((32,), 7, jnp.int32)
    res = unique_with_counts(ids)
    assert int(res.num_unique) == 1
    assert int(res.counts[0]) == 32
    assert int(res.unique_ids[0]) == 7


def test_bucket_unbucket_roundtrip():
    rng = np.random.default_rng(1)
    n, shards = 64, 4
    ids = jnp.asarray(rng.integers(0, 1000, size=n))
    valid = jnp.asarray(rng.random(n) > 0.2)
    res = bucket_by_owner(ids, valid, shards, capacity=n)
    assert int(res.overflow) == 0
    # every valid id landed in its owner bucket
    b_ids = np.asarray(res.bucket_ids)
    b_valid = np.asarray(res.bucket_valid)
    for s in range(shards):
        got = sorted(b_ids[s][b_valid[s]].tolist())
        expect = sorted(int(i) for i, v in zip(np.asarray(ids), np.asarray(valid))
                        if v and i % shards == s)
        assert got == expect
    # unbucket returns each element's own payload
    payload = b_ids[..., None].astype(np.float32)  # payload = the id itself
    back = unbucket(jnp.asarray(payload), res.owner, res.slot)
    back = np.asarray(back)[:, 0]
    np.testing.assert_array_equal(
        back[np.asarray(valid)], np.asarray(ids)[np.asarray(valid)].astype(np.float32))
    # invalid elements read back zeros
    assert np.all(back[~np.asarray(valid)] == 0)


def test_bucket_overflow_counted():
    ids = jnp.zeros((16,), jnp.int32)  # all owner 0
    valid = jnp.ones((16,), bool)
    res = bucket_by_owner(ids, valid, num_shards=4, capacity=4)
    assert int(res.overflow) == 12
    assert int(res.bucket_valid.sum()) == 4


@pytest.mark.parametrize("pair", [False, True])
@pytest.mark.parametrize("cap_frac", [1.0, 0.3])
def test_unique_and_route_matches_split_pipeline(pair, cap_frac):
    """The fused single-sort plan must agree with unique_with_counts +
    bucket_by_owner on everything order-independent: the unique id SET, the
    inverse mapping contract (unique_ids[inverse[i]] == ids[i]), counts per
    id, per-owner bucket CONTENT, and the overflow count."""
    from openembedding_tpu.ops.dedup import unique_and_route

    rng = np.random.default_rng(0)
    n, S = 257, 4
    raw = rng.integers(0, 64, size=n)
    # validity is a function of the id VALUE (negative = padding), exactly
    # like `_id_valid` in the protocol — never a per-occurrence coin flip
    invalid_values = {3, 17, 42}
    raw = np.where(np.isin(raw, list(invalid_values)), -1, raw)
    mask = raw < 0
    if pair:
        from openembedding_tpu.ops.id64 import np_split_ids
        ids64 = np.where(raw < 0, -1, raw.astype(np.int64) + (1 << 40))
        ids = jnp.asarray(np_split_ids(ids64))
    else:
        ids = jnp.asarray(raw.astype(np.int32))
        ids64 = raw.astype(np.int64)
    valid = jnp.asarray(~mask)
    cap = max(1, int(cap_frac * n / S))

    uniq, buckets = jax.jit(
        lambda i, v: unique_and_route(i, v, S, cap))(ids, valid)

    # oracle: the split pipeline (validity recomputed on the unique ids, the
    # way make_plan's old path did)
    o_uniq = unique_with_counts(ids)
    if pair:
        from openembedding_tpu.ops.id64 import pair_valid
        o_valid_u = (o_uniq.counts > 0) & pair_valid(o_uniq.unique_ids)
    else:
        o_valid_u = (o_uniq.counts > 0) & (o_uniq.unique_ids >= 0)
    o_buckets = bucket_by_owner(o_uniq.unique_ids, o_valid_u, S, cap)

    # inverse contract on the fused result
    u = np.asarray(uniq.unique_ids)
    inv = np.asarray(uniq.inverse)
    got_back = u[inv]
    np.testing.assert_array_equal(got_back, np.asarray(ids))

    # counts per id agree (compare as {id: count} dicts over valid slots)
    def count_map(uq, cnts):
        uq, cnts = np.asarray(uq), np.asarray(cnts)
        out = {}
        for i in range(len(cnts)):
            if cnts[i] > 0:
                key = tuple(uq[i]) if uq.ndim == 2 else int(uq[i])
                out[key] = int(cnts[i])
        return out

    assert count_map(uniq.unique_ids, uniq.counts) == \
        count_map(o_uniq.unique_ids, o_uniq.counts)

    # bucket content per owner agrees as SETS (order within a bucket differs)
    def bucket_sets(b):
        ids_np, valid_np = np.asarray(b.bucket_ids), np.asarray(b.bucket_valid)
        out = []
        for s in range(S):
            rows = ids_np[s][valid_np[s]]
            out.append({tuple(r) if rows.ndim == 2 else int(r) for r in rows})
        return out

    g, o = bucket_sets(buckets), bucket_sets(o_buckets)
    if cap_frac >= 1.0:
        assert g == o
        assert int(buckets.overflow) == int(o_buckets.overflow) == 0
    else:
        # under capacity pressure both drop the same NUMBER of ids per owner
        # (which ids differ by intra-bucket order)
        assert [len(x) for x in g] == [len(x) for x in o]
        assert int(buckets.overflow) == int(o_buckets.overflow)


def test_mesh_training_with_id_zero_matches_single_device():
    """REGRESSION for the sentinel-filled exchange: id 0 is a real id and an
    all-zeros bucket slot must NOT alias it. Train a stream saturated with
    id 0 (plus shard-boundary ids) on the mesh and on one device — losses
    and the id-0 row must match exactly."""
    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo  # noqa: F401
    from openembedding_tpu.embedding import lookup
    from openembedding_tpu.initializers import Constant
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.parallel import MeshTrainer, make_mesh
    import dataclasses

    S = 8
    rng = np.random.default_rng(0)

    def build(cls, loss_scale=1.0, **kw):
        m = make_deepfm(vocabulary=64, dim=4, hidden=(8,))
        m.specs["categorical"] = dataclasses.replace(
            m.specs["categorical"], initializer=Constant(0.0))
        lf = m.loss_fn
        m.loss_fn = lambda lo, la, *a: loss_scale * lf(lo, la, *a)
        return cls(m, embed.Adagrad(learning_rate=0.1), **kw)

    # every batch drowns in id 0 and the shard-boundary ids 0..S
    batches = []
    for i in range(3):
        ids = rng.integers(0, 64, (16, 4)).astype(np.int32)
        ids[:, 0] = 0
        ids[: S + 1, 1] = np.arange(S + 1)
        batches.append({"sparse": {"categorical": ids},
                        "dense": rng.standard_normal((16, 13)).astype(np.float32),
                        "label": rng.integers(0, 2, (16,)).astype(np.float32)})

    single = build(Trainer, loss_scale=float(S))
    s_state = single.init(batches[0])
    sstep = single.jit_train_step()
    s_losses = []
    for b in batches:
        s_state, m = sstep(s_state, b)
        s_losses.append(float(m["loss"]))

    mesh_tr = build(MeshTrainer, mesh=make_mesh())
    m_state = mesh_tr.init(batches[0])
    mstep = mesh_tr.jit_train_step(batches[0], m_state)
    m_losses = []
    for b in batches:
        m_state, m = mstep(m_state, b)
        m_losses.append(float(m["loss"]))

    # 3 steps of Adagrad compound float-order differences between the
    # psum'd-grad and scaled-loss formulations; an aliasing bug would be
    # gross (zeroed/duplicated rows), not 1e-3 (observed drift on the CPU
    # XLA in this container is 1.3e-3 — platform-dependent reduction order,
    # same reasoning as the test_planted_auc platform gating)
    np.testing.assert_allclose(m_losses, np.asarray(s_losses) / S, rtol=3e-3)
    spec = single.model.specs["categorical"]
    probe = jnp.asarray(np.arange(S + 1, dtype=np.int32))
    want = np.asarray(lookup(spec, s_state.tables["categorical"], probe))
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from openembedding_tpu.parallel.sharded import sharded_lookup
    pull = jax.jit(jax.shard_map(
        partial(sharded_lookup, spec, axis=mesh_tr.axis),
        mesh=mesh_tr.mesh,
        in_specs=(mesh_tr._table_pspec(spec), P()),
        out_specs=P(), check_vma=False))
    got = np.asarray(pull(m_state.tables["categorical"], probe))
    # bf16 dense towers + 3 steps of reduction-order drift bound parity
    # near 1e-4 abs; an aliased/missed id-0 update would be O(0.05+)
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-3)


def test_mesh_step_compiles_three_all_to_alls():
    """Structural pin on the exchange wire: one full train step moves exactly
    THREE all_to_alls per DIM-GROUP — ids out, rows back, grads+counts out
    (the validity mask rides the id sentinel, the counts ride the grad
    payload). deepfm's folded layout is one table = one group, so the budget
    here is 3; the multi-group fusion pin (3 tables, 2 groups -> 6, not 9)
    lives in tests/test_wire.py. A fourth collective reappearing per group is
    a protocol regression."""
    import re
    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    model = make_deepfm(vocabulary=1 << 12, dim=4, hidden=(8,))
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=make_mesh())
    b = next(synthetic_criteo(32, id_space=1 << 12, steps=1, seed=0))
    state = tr.init(b)
    step = tr.jit_train_step(b, state)
    txt = step.lower(state, b).compile().as_text()
    # op instantiations only; async backends emit start/done pairs — count
    # the starts
    n = len(re.findall(r" all-to-all(?:-start)?\(", txt))
    assert n == 3, f"expected 3 all-to-alls in the step, found {n}"


def test_mesh_bf16_table_counts_ride_two_lanes():
    """bfloat16 tables push bf16 payloads: the duplicate count bitcasts into
    TWO bf16 lanes and must round-trip exactly. TestOptimizer is the only
    count-DIVIDING optimizer, so a corrupted count shows up as a grossly
    wrong update, not a rounding blip."""
    import dataclasses
    import openembedding_tpu as embed
    from openembedding_tpu.embedding import lookup
    from openembedding_tpu.initializers import Constant
    from openembedding_tpu.model import EmbeddingModel, Trainer
    from openembedding_tpu.models import make_lr
    from openembedding_tpu.optimizers import TestOptimizer
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    def build(cls, **kw):
        e = embed.Embedding(64, 4, name="categorical", datatype="bfloat16",
                            embeddings_initializer=Constant(0.0))
        lr = make_lr(vocabulary=64)
        m = EmbeddingModel(lr.module, [e], loss_fn=lr.loss_fn)
        return cls(m, TestOptimizer(learning_rate=0.5), **kw)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (32, 4)).astype(np.int32)
    ids[:, 0] = 7  # 32 duplicates of id 7: count division must see 32
    batch = {"sparse": {"categorical": ids}, "label":
             rng.integers(0, 2, (32,)).astype(np.float32)}

    single = build(Trainer)
    s_state = single.init(batch)
    s_state, _ = single.jit_train_step()(s_state, batch)

    mesh_tr = build(MeshTrainer, mesh=make_mesh())
    m_state = mesh_tr.init(batch)
    m_state, _ = mesh_tr.jit_train_step(batch, m_state)(m_state, batch)

    spec = single.model.specs["categorical"]
    probe = jnp.asarray(np.unique(ids).astype(np.int32))
    want = np.asarray(lookup(spec, s_state.tables["categorical"],
                             probe)).astype(np.float32)
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from openembedding_tpu.parallel.sharded import sharded_lookup
    pull = jax.jit(jax.shard_map(
        partial(sharded_lookup, spec, axis=mesh_tr.axis),
        mesh=mesh_tr.mesh,
        in_specs=(mesh_tr._table_pspec(spec), P()),
        out_specs=P(), check_vma=False))
    got = np.asarray(pull(m_state.tables["categorical"],
                          probe)).astype(np.float32)
    # a mangled count would divide by garbage (flip-state updates are
    # O(flip/count)); bf16 rounding is the only legitimate difference
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)
    assert np.abs(got).max() > 0  # the step really updated rows


@pytest.mark.parametrize("case", ["single", "all_invalid", "all_same"])
def test_unique_and_route_edges(case):
    """Degenerate inputs through the fused plan: one id, nothing valid, one
    id duplicated across the whole batch."""
    from openembedding_tpu.ops.dedup import bucket_validity, unique_and_route

    S, cap = 4, 8
    if case == "single":
        ids = jnp.asarray(np.asarray([5], np.int32))
        valid = jnp.asarray([True])
    elif case == "all_invalid":
        ids = jnp.asarray(np.full((16,), -1, np.int32))
        valid = jnp.zeros((16,), bool)
    else:
        ids = jnp.asarray(np.full((16,), 7, np.int32))
        valid = jnp.ones((16,), bool)
    uniq, buckets = jax.jit(
        lambda i, v: unique_and_route(i, v, S, cap))(ids, valid)

    occupancy = int(np.asarray(bucket_validity(buckets.bucket_ids)).sum())
    if case == "single":
        assert int(uniq.num_unique) == 1
        assert occupancy == 1
        assert int(buckets.owner[0]) == 5 % S
    elif case == "all_invalid":
        assert occupancy == 0
        assert int(buckets.overflow) == 0
        # every element routed to the invalid pseudo-owner
        assert np.all(np.asarray(buckets.owner) == S)
    else:
        assert int(uniq.num_unique) == 1
        assert occupancy == 1
        assert int(np.asarray(uniq.counts)[0]) == 16
        np.testing.assert_array_equal(np.asarray(uniq.inverse), 0)
