"""Unit tests for the static-shape dedup / bucketing primitives (the counterparts of
the reference's client-side hot loops, `EmbeddingPullOperator.cpp:60-112`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from openembedding_tpu.ops.dedup import bucket_by_owner, unbucket, unique_with_counts


@pytest.mark.parametrize("n,vocab", [(16, 5), (128, 1000), (64, 2)])
def test_unique_with_counts_matches_numpy(n, vocab):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=n)
    res = jax.jit(unique_with_counts)(jnp.asarray(ids))
    expect_u, expect_c = np.unique(ids, return_counts=True)
    k = int(res.num_unique)
    assert k == len(expect_u)
    np.testing.assert_array_equal(np.asarray(res.unique_ids)[:k], expect_u)
    np.testing.assert_array_equal(np.asarray(res.counts)[:k], expect_c)
    # padding slots have count 0
    assert np.all(np.asarray(res.counts)[k:] == 0)
    # inverse maps each id back to its unique slot
    np.testing.assert_array_equal(np.asarray(res.unique_ids)[np.asarray(res.inverse)], ids)


def test_unique_single_value():
    ids = jnp.full((32,), 7, jnp.int32)
    res = unique_with_counts(ids)
    assert int(res.num_unique) == 1
    assert int(res.counts[0]) == 32
    assert int(res.unique_ids[0]) == 7


def test_bucket_unbucket_roundtrip():
    rng = np.random.default_rng(1)
    n, shards = 64, 4
    ids = jnp.asarray(rng.integers(0, 1000, size=n))
    valid = jnp.asarray(rng.random(n) > 0.2)
    res = bucket_by_owner(ids, valid, shards, capacity=n)
    assert int(res.overflow) == 0
    # every valid id landed in its owner bucket
    b_ids = np.asarray(res.bucket_ids)
    b_valid = np.asarray(res.bucket_valid)
    for s in range(shards):
        got = sorted(b_ids[s][b_valid[s]].tolist())
        expect = sorted(int(i) for i, v in zip(np.asarray(ids), np.asarray(valid))
                        if v and i % shards == s)
        assert got == expect
    # unbucket returns each element's own payload
    payload = b_ids[..., None].astype(np.float32)  # payload = the id itself
    back = unbucket(jnp.asarray(payload), res.owner, res.slot)
    back = np.asarray(back)[:, 0]
    np.testing.assert_array_equal(
        back[np.asarray(valid)], np.asarray(ids)[np.asarray(valid)].astype(np.float32))
    # invalid elements read back zeros
    assert np.all(back[~np.asarray(valid)] == 0)


def test_bucket_overflow_counted():
    ids = jnp.zeros((16,), jnp.int32)  # all owner 0
    valid = jnp.ones((16,), bool)
    res = bucket_by_owner(ids, valid, num_shards=4, capacity=4)
    assert int(res.overflow) == 12
    assert int(res.bucket_valid.sum()) == 4
