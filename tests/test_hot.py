"""Skew-aware hot-row replication (round 10): replicated heavy-hitter cache
on the sharded exchange (`parallel/sharded.py` "HOT-ROW REPLICATION",
`MeshTrainer(hot_rows=...)`).

Acceptance (ISSUE 5):
- fp32 parity: with OETPU_WIRE=fp32 a hot-enabled train step is BIT-EXACT vs
  hot-disabled on the same batches — losses, pulled rows, and (after
  `hot_sync`) weights and optimizer slots — on the per-table protocol, the
  fused grouped exchange, AND pair-key hash tables;
- persistence oblivious: checkpoints written by a hot-enabled trainer are
  byte-identical to the hot-off world's;
- Zipf e2e: `hot.hit_ratio` tracks the sketch-predicted coverage of the
  promoted set and `exchange.shard_imbalance` drops when the cache turns on;
- the default path stays free: hot_rows=0 attaches no cache state and traces
  no extra collectives (same 3-a2a-per-group program as before the feature).
"""

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.model import EmbeddingModel
from openembedding_tpu.parallel import MeshTrainer, make_mesh
from openembedding_tpu.utils import metrics

S = 8  # conftest forces 8 virtual CPU devices
B = 64


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics._REGISTRY.clear()
    yield
    metrics._REGISTRY.clear()


class _Tower(nn.Module):
    """Two dim-8 tables (array + hash) -> logits (B,)."""

    @nn.compact
    def __call__(self, embedded, dense):
        bias = self.param("bias", nn.initializers.zeros, (1,), jnp.float32)
        out = (jnp.sum(embedded["a"].astype(jnp.float32), axis=(1, 2))
               + jnp.sum(embedded["b"].astype(jnp.float32), axis=(1, 2)))
        return out + bias[0]


def _model(vocab=256):
    return EmbeddingModel(_Tower(), [
        embed.Embedding(vocab, 8, name="a"),
        embed.Embedding(-1, 8, name="b", capacity=4096),
    ])


def _batch(rng, vocab=256, hash_space=1 << 40, hash_dtype=np.int64):
    a = rng.integers(0, vocab, (B, 4)).astype(np.int32)
    b = rng.integers(0, hash_space, (B, 3)).astype(hash_dtype)
    # planted heavy hitters (duplicate-heavy so counts > 1 cross the push)
    a[:, 0] = np.array([7, 13])[rng.integers(0, 2, B)]
    b[:, 0] = hash_space - 13
    return {"sparse": {"a": a, "b": b},
            "label": rng.integers(0, 2, (B,)).astype(np.float32)}


_HOT_IDS = {"a": np.array([7, 13], np.int64),
            "b": np.array([(1 << 40) - 13], np.int64)}


def _train(trainer, batches, refresh_at=None, hot_ids=None):
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    losses, stats = [], None
    for i, b in enumerate(batches):
        if refresh_at is not None and i == refresh_at:
            state = trainer.refresh_hot_rows(state, hot_ids=hot_ids)
        state, m = step(state, b)
        losses.append(float(m["loss"]))
        stats = jax.device_get(m["stats"])
    return state, losses, stats


def _probe(trainer, state, name, probe_ids):
    """Read rows by id through the hot-aware sharded lookup."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from openembedding_tpu.parallel.sharded import sharded_lookup
    spec = trainer.model.specs[name]
    fn = jax.jit(jax.shard_map(
        partial(sharded_lookup, spec, axis=trainer.axis),
        mesh=trainer.mesh,
        in_specs=(trainer._table_pspec(spec), P()), out_specs=P(),
        check_vma=False))
    return np.asarray(fn(state.tables[name], jnp.asarray(probe_ids)))


def _assert_synced_tables_equal(s_off, s_on):
    for name in s_off.tables:
        t0, t1 = s_off.tables[name], s_on.tables[name]
        np.testing.assert_array_equal(np.asarray(t0.weights),
                                      np.asarray(t1.weights), err_msg=name)
        for k in t0.slots:
            np.testing.assert_array_equal(
                np.asarray(t0.slots[k]), np.asarray(t1.slots[k]),
                err_msg=f"{name}/{k}")
        if t0.keys is not None:
            np.testing.assert_array_equal(np.asarray(t0.keys),
                                          np.asarray(t1.keys), err_msg=name)


@pytest.mark.parametrize("group_exchange", [True, False])
def test_fp32_parity_hot_on_vs_off(group_exchange):
    """THE acceptance pin: hot-enabled training (promote mid-run, train
    across the refresh) is bit-exact vs hot-disabled at fp32 wire — losses
    every step, row reads by id, and the shard arrays (weights + optimizer
    slots + hash keys) after writeback. Covers the fused grouped exchange
    AND the per-table fallback protocol."""
    rng = np.random.default_rng(1)
    batches = [_batch(rng) for _ in range(4)]

    def run(hot_rows):
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire="fp32",
                         group_exchange=group_exchange, hot_rows=hot_rows)
        state, losses, stats = _train(
            tr, batches, refresh_at=2 if hot_rows else None,
            hot_ids=_HOT_IDS)
        if hot_rows:
            state = tr.hot_sync(state)
        return tr, state, losses, stats

    tr0, s_off, l_off, _ = run(0)
    tr1, s_on, l_on, st_on = run(64)
    assert l_off == l_on
    # the cache actually served traffic (planted ids dominate the batches)
    assert int(st_on["a/hot_hits"]) > 0 and int(st_on["b/hot_hits"]) > 0
    assert float(st_on["a/hot_bytes_saved"]) > 0
    probes = {"a": np.arange(256, dtype=np.int32),
              "b": np.unique(np.concatenate(
                  [b["sparse"]["b"].reshape(-1) for b in batches]))}
    for name, ids in probes.items():
        np.testing.assert_array_equal(_probe(tr0, s_off, name, ids),
                                      _probe(tr1, s_on, name, ids),
                                      err_msg=name)
    _assert_synced_tables_equal(s_off, s_on)


def test_fp32_parity_pair_key_hash_tables():
    """x64-off: hash tables key in the split-pair uint32 layout; the hot
    probe, local gather, reduced push and writeback must all ride the pair
    machinery bit-exactly."""
    with jax.enable_x64(False):
        rng = np.random.default_rng(2)
        batches = [_batch(rng, hash_space=1 << 20, hash_dtype=np.int32)
                   for _ in range(3)]
        hot_ids = {"a": np.array([7, 13], np.int64),
                   "b": np.array([(1 << 20) - 13], np.int64)}

        def run(hot_rows):
            tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                             mesh=make_mesh(), wire="fp32",
                             hot_rows=hot_rows)
            state, losses, _ = _train(
                tr, batches, refresh_at=1 if hot_rows else None,
                hot_ids=hot_ids)
            assert state.tables["b"].keys.ndim == 2  # pair-keyed
            if hot_rows:
                assert state.tables["b"].hot.keys.ndim == 2
                state = tr.hot_sync(state)
            return tr, state, losses

        tr0, s_off, l_off = run(0)
        tr1, s_on, l_on = run(32)
        assert l_off == l_on
        _assert_synced_tables_equal(s_off, s_on)


def test_checkpoint_byte_identical_and_load_reattaches(tmp_path):
    """Persistence obliviousness: a hot-enabled trainer's checkpoint equals
    the hot-off world's byte for byte (hot rows write back into owner shards
    at save time); `MeshTrainer.load` re-attaches + re-gathers the cache, and
    training continues bit-exactly."""
    rng = np.random.default_rng(3)
    batches = [_batch(rng) for _ in range(4)]

    def run(hot_rows, path):
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire="fp32", hot_rows=hot_rows)
        state, _, _ = _train(tr, batches[:2],
                             refresh_at=1 if hot_rows else None,
                             hot_ids=_HOT_IDS)
        tr.save(state, str(path), model_sign="t")
        return tr, state

    tr0, s_off = run(0, tmp_path / "off")
    tr1, s_on = run(64, tmp_path / "on")
    import os
    for root, _dirs, files in os.walk(tmp_path / "off"):
        for fn in files:
            p_off = os.path.join(root, fn)
            p_on = p_off.replace(str(tmp_path / "off"), str(tmp_path / "on"))
            with open(p_off, "rb") as fa, open(p_on, "rb") as fb:
                a, b = fa.read(), fb.read()
            if fn == "model_meta":
                continue  # carries the save-time uuid sign; payloads matter
            assert a == b, f"checkpoint file differs: {fn}"

    # load into a FRESH hot-enabled trainer: cache re-attaches (empty set —
    # the pre-load state here is fresh) and refresh + training keep parity
    def resume(hot_rows, path):
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire="fp32", hot_rows=hot_rows)
        state = tr.init(batches[0])
        state = tr.load(state, str(path))
        if hot_rows:
            assert state.tables["a"].hot is not None
            state = tr.refresh_hot_rows(state, hot_ids=_HOT_IDS)
        step = tr.jit_train_step(batches[0], state)
        losses = []
        for b in batches[2:]:
            state, m = step(state, b)
            losses.append(float(m["loss"]))
        return losses

    assert resume(0, tmp_path / "off") == resume(64, tmp_path / "on")


def test_incremental_persist_deltas_byte_identical(tmp_path):
    """The sync/delta feed stays oblivious too: `IncrementalPersister` deltas
    (touched-row payloads read straight off the shards) are byte-identical
    hot-on vs hot-off — the persister's hot_sync hook writes the cache back
    before every snapshot."""
    import os

    from openembedding_tpu.persist import IncrementalPersister, PersistPolicy
    rng = np.random.default_rng(4)
    batches = [_batch(rng) for _ in range(3)]

    def run(hot_rows, root):
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire="fp32", hot_rows=hot_rows)
        state = tr.init(batches[0])
        if hot_rows:
            state = tr.refresh_hot_rows(state, hot_ids=_HOT_IDS)
        step = tr.jit_train_step(batches[0], state)
        with IncrementalPersister(tr, tr.model, str(root), window=1,
                                  policy=PersistPolicy(every_steps=1),
                                  full_every=100) as p:
            for b in batches:
                state, _m = step(state, b)
                p.maybe_persist(state, batch=b)
            p.wait()

    run(0, tmp_path / "off")
    run(64, tmp_path / "on")
    delta_tables = []
    for root, _dirs, files in os.walk(tmp_path / "off"):
        for fn in files:
            if not fn.startswith("table_"):
                continue
            delta_tables.append(fn)
            p_off = os.path.join(root, fn)
            p_on = p_off.replace(str(tmp_path / "off"), str(tmp_path / "on"))
            a = np.load(p_off)
            b = np.load(p_on)
            assert sorted(a.files) == sorted(b.files), fn
            for k in a.files:
                np.testing.assert_array_equal(a[k], b[k],
                                              err_msg=f"{fn}:{k}")
    assert delta_tables  # the runs actually produced delta payloads


def test_zipf_hit_ratio_matches_sketch_coverage_and_imbalance_drops():
    """Zipf e2e acceptance: promote the sketch's top-K; the live
    `hot.hit_ratio` gauge must track the sketch-predicted coverage of that
    set, and `exchange.shard_imbalance` must drop vs cache-off (the hot mass
    leaves `shard_positions`)."""
    from openembedding_tpu.utils.sketch import SkewMonitor
    rng = np.random.default_rng(5)
    vocab = 1 << 12
    # heavy head, all landing on shard 5 (ids = 8k + 5): the round-9 planted
    # skew case — cache-off imbalance is unambiguous
    hot_pool = (np.arange(16) * S + 5).astype(np.int64)
    ids = rng.integers(0, vocab, (B, 26))
    mask = rng.random((B, 26)) < 0.6
    ids[mask] = hot_pool[rng.integers(0, 16, mask.sum())]

    model = EmbeddingModel(_Tower(), [
        embed.Embedding(vocab, 8, name="a"),
        embed.Embedding(-1, 8, name="b", capacity=4096),
    ])
    batch = {"sparse": {"a": ids.astype(np.int32),
                        "b": (ids + 1).astype(np.int64)},
             "label": rng.integers(0, 2, (B,)).astype(np.float32)}

    mon = SkewMonitor(k=64, sync=True)
    mon.observe("a", batch["sparse"]["a"])
    H = 16
    predicted = dict(mon.sketch("a").coverage([H]))[H]

    def run(hot_rows):
        metrics._REGISTRY.clear()
        tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire="fp32", hot_rows=hot_rows)
        state = tr.init(batch)
        if hot_rows:
            state = tr.refresh_hot_rows(state, monitor=mon)
        step = tr.jit_train_step(batch, state)
        _state, m = step(state, batch)
        metrics.record_step_stats(m["stats"])
        return metrics.report()

    rep_off = run(0)
    rep_on = run(H)
    imb_off = rep_off['exchange.shard_imbalance{table="a"}']
    imb_on = rep_on['exchange.shard_imbalance{table="a"}']
    hit = rep_on['hot.hit_ratio{table="a"}']
    # the sketch saw exactly this stream, so coverage is near-exact here
    assert abs(hit - predicted) < 0.05, (hit, predicted)
    assert hit > 0.5
    assert imb_on < imb_off - 0.5, (imb_on, imb_off)
    assert rep_on['hot.bytes_saved{table="a"}'] > 0
    # gauges survive a periodic report(reset=True) like other exchange gauges
    metrics.report(reset=True)
    rep2 = metrics.report()
    assert rep2['hot.hit_ratio{table="a"}'] == hit


def test_hot_off_traces_no_extra_collectives():
    """The default path stays free: hot_rows=0 attaches no cache state and
    compiles the SAME collective set as before the feature (3 a2a per
    dim-group, no all-gather); hot-on keeps the a2a count and adds only the
    backward all_gathers."""
    import re
    rng = np.random.default_rng(6)
    b = _batch(rng)

    def hlo(hot_rows):
        tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                         mesh=make_mesh(), wire="fp32", hot_rows=hot_rows)
        state = tr.init(b)
        if hot_rows:
            assert state.tables["a"].hot is not None
        else:
            assert state.tables["a"].hot is None
        step = tr.jit_train_step(b, state)
        return step.lower(state, b).compile().as_text()

    txt_off = hlo(0)
    txt_on = hlo(64)

    def count(pat, txt):
        return len(re.findall(pat, txt))

    a2a = r" all-to-all(?:-start)?\("
    ar = r" all-reduce(?:-start)?\("
    assert count(a2a, txt_off) == 3  # one dim-8 group: ids, rows, grads
    assert count(a2a, txt_on) == 3   # hot removes payload, not collectives
    # the default path adds NO collectives; hot-on adds only the dense
    # psums of the hot grad/count aggregates (all-reduce, never a2a)
    assert count(ar, txt_on) > count(ar, txt_off)


def test_refresh_is_static_shapes_no_rejit():
    """Promote/demote swaps array contents, never shapes: the SAME jitted
    step keeps running across refreshes with different hot sets (and the
    lifecycle fns compile once per mode). The never-re-jit rule is asserted
    EXECUTABLY via utils/guards.assert_no_recompile: any retrace raises."""
    from openembedding_tpu.utils.guards import assert_no_recompile
    rng = np.random.default_rng(7)
    batches = [_batch(rng) for _ in range(3)]
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="fp32", hot_rows=32)
    state = tr.init(batches[0])
    step = assert_no_recompile(tr.jit_train_step(batches[0], state),
                               label="hot_train_step")
    state, _ = step(state, batches[0])
    state = tr.refresh_hot_rows(state, hot_ids={"a": np.array([7], np.int64)})
    state, _ = step(state, batches[1])
    state = tr.refresh_hot_rows(
        state, hot_ids={"a": np.array([13, 21], np.int64),
                        "b": _HOT_IDS["b"]})
    state, m = step(state, batches[2])
    assert np.isfinite(float(m["loss"]))
    assert step.trace_count() == 1  # three steps, two refreshes, ONE program
    assert set(tr._hot_fns) == {"refresh"}  # one compiled refresh, reused
    # demoted id 7 must have been written back: reads still see its training
    rows = _probe(tr, tr.hot_sync(state), "a", np.array([7, 13], np.int32))
    assert np.abs(rows).sum() > 0


def test_hot_rows_inert_on_one_device_mesh():
    """hot_rows on a 1-device mesh is silently inert (the shard IS local);
    the protocol itself rejects a stray hot cache at S=1 loudly."""
    rng = np.random.default_rng(8)
    b = _batch(rng)
    tr = MeshTrainer(_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(jax.devices()[:1]), hot_rows=64)
    assert not tr.hot_enabled
    state = tr.init(b)
    assert state.tables["a"].hot is None
    state = tr.refresh_hot_rows(state)  # no-op, not an error
    step = tr.jit_train_step(b, state)
    _state, m = step(state, b)
    assert np.isfinite(float(m["loss"]))
