"""Packed weights+slots layout inside `Trainer.train_many` (ops/sparse.py).

The packed form exists only inside the scan; these tests pin (a) exact
numeric parity against the split-layout step path, (b) the width gate, and
(c) that the state coming out of `train_many` is back in the split layout
(checkpoints/serving/offload never see packed arrays).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.ops.sparse import (packed_layout, pack_table,
                                          sparse_apply_dense_table,
                                          sparse_apply_packed_table,
                                          unpack_table)


def test_packed_layout_gate():
    slots = {"accum": jnp.zeros((4, 10), jnp.float32)}
    assert packed_layout(10, slots) == (("accum", 10),)      # 20 <= 32
    assert packed_layout(10, {}) is None                     # no slots
    # 65 + 65 = 130: the padded-copy regime — refuse
    assert packed_layout(65, {"accum": jnp.zeros((4, 65), jnp.float32)}) is None
    # exact lane multiple is fine
    assert packed_layout(64, {"accum": jnp.zeros((4, 64), jnp.float32)}) == \
        (("accum", 64),)
    # non-f32 slots (none exist today; the gate still refuses)
    assert packed_layout(4, {"s": jnp.zeros((4, 4), jnp.bfloat16)}) is None


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((16, 6)), jnp.float32)
    slots = {"a": jnp.asarray(rng.standard_normal((16, 6)), jnp.float32),
             "b": jnp.asarray(rng.standard_normal((16, 1)), jnp.float32)}
    lay = packed_layout(6, slots)
    packed = pack_table(w, slots, lay)
    assert packed.shape == (16, 13)
    w2, s2 = unpack_table(packed, lay, 6, w.dtype)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(w2))
    for k in slots:
        np.testing.assert_array_equal(np.asarray(slots[k]), np.asarray(s2[k]))


@pytest.mark.parametrize("opt_name", ["adagrad", "adam", "ftrl"])
def test_packed_apply_matches_split(opt_name):
    """One fused update through both layouts: bit-identical tables."""
    opt = {"adagrad": embed.Adagrad(learning_rate=0.1),
           "adam": embed.Adam(learning_rate=0.01),
           "ftrl": embed.Ftrl(learning_rate=0.1)}[opt_name]
    dim, rows, n = 6, 64, 40
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((rows, dim)), jnp.float32)
    slots = opt.init_slots(rows, dim)
    lay = packed_layout(dim, slots)
    if lay is None:
        pytest.skip(f"{opt_name}: not packable at dim {dim}")
    ids = jnp.asarray(rng.integers(-1, rows, n), jnp.int32)  # incl. invalid
    g = jnp.asarray(rng.standard_normal((n, dim)), jnp.float32)

    sw, ss = jax.jit(lambda w, s: sparse_apply_dense_table(opt, w, s, ids, g))(
        w, slots)
    packed = jax.jit(lambda w, s: sparse_apply_packed_table(
        opt, pack_table(w, s, lay), lay, dim, ids, g))(w, slots)
    pw, ps = unpack_table(packed, lay, dim, w.dtype)
    np.testing.assert_array_equal(np.asarray(sw), np.asarray(pw))
    for k in ss:
        np.testing.assert_array_equal(np.asarray(ss[k]), np.asarray(ps[k]))


def test_train_many_packed_matches_step_loop():
    """`jit_train_many` (packed scan) == sequential `jit_train_step` (split):
    same losses, same final tables, and the returned state is split-layout."""
    V, steps = 2048, 6
    model = make_deepfm(vocabulary=V, dim=8)
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batches = list(synthetic_criteo(64, id_space=V, steps=steps, seed=5))
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)

    state = trainer.init(batches[0])
    # sanity: this model/optimizer combination actually engages packing
    assert trainer._packed_layouts(state), "expected a packable table"

    sm, metrics = trainer.jit_train_many()(state, stacked)
    assert metrics["loss"].shape == (steps,)

    state2 = trainer.init(batches[0])
    step = trainer.jit_train_step()
    losses = []
    for b in batches:
        state2, m = step(state2, b)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses,
                               rtol=0, atol=0)
    (name, spec), = model.ps_specs().items()
    # split layout on exit: weights have the spec's width again
    assert sm.tables[name].weights.shape[1] == spec.output_dim
    assert set(sm.tables[name].slots) == set(state2.tables[name].slots)
    np.testing.assert_array_equal(np.asarray(sm.tables[name].weights),
                                  np.asarray(state2.tables[name].weights))
    for k, v in state2.tables[name].slots.items():
        np.testing.assert_array_equal(np.asarray(sm.tables[name].slots[k]),
                                      np.asarray(v))


def test_train_many_packed_hash_table_matches_step_loop():
    """Hash-table (input_dim=-1) variables pack too: same probe/insert/
    overflow semantics, one gather/scatter pair. Exact parity vs the split
    step path, including the keys array and overflow counter."""
    from openembedding_tpu.embedding import Embedding
    from openembedding_tpu.model import EmbeddingModel
    from openembedding_tpu.models.ctr import LogisticRegression

    steps = 5
    model = EmbeddingModel(
        module=LogisticRegression(),
        embeddings=[Embedding(input_dim=-1, output_dim=8, name="categorical",
                              capacity=512)])
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.1))
    rng = np.random.default_rng(11)
    batches = [{"sparse": {"categorical": rng.integers(0, 10_000, (32, 4))
                           .astype(np.int64)},
                "dense": None,
                "label": rng.integers(0, 2, (32,)).astype(np.float32)}
               for _ in range(steps)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs) if xs[0] is not None else None, *batches,
        is_leaf=lambda x: x is None)

    state = trainer.init(batches[0])
    assert "categorical" in trainer._packed_layouts(state)
    sm, metrics = trainer.jit_train_many()(state, stacked)

    state2 = trainer.init(batches[0])
    step = trainer.jit_train_step()
    losses = []
    for b in batches:
        state2, m = step(state2, b)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses,
                               rtol=0, atol=0)
    np.testing.assert_array_equal(np.asarray(sm.tables["categorical"].keys),
                                  np.asarray(state2.tables["categorical"].keys))
    assert int(sm.tables["categorical"].overflow) == int(state2.tables["categorical"].overflow)
    np.testing.assert_array_equal(np.asarray(sm.tables["categorical"].weights),
                                  np.asarray(state2.tables["categorical"].weights))
    for k, v in state2.tables["categorical"].slots.items():
        np.testing.assert_array_equal(np.asarray(sm.tables["categorical"].slots[k]),
                                      np.asarray(v))


def test_mesh_train_many_packed_matches_step_loop():
    """MeshTrainer's scan packs per shard: jit_train_many (packed, plan-reusing
    sharded apply) == sequential jit_train_step (split) on the same 8-device
    mesh — losses and final sharded tables exact."""
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    V, steps = 4096, 4
    model = make_deepfm(vocabulary=V, dim=8)
    mesh = make_mesh()
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=mesh)
    batches = list(synthetic_criteo(64, id_space=V, steps=steps, seed=13))
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)

    state = trainer.init(batches[0])
    many = trainer.jit_train_many(stacked, state)
    sm, metrics = many(state, stacked)

    trainer2 = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=mesh)
    state2 = trainer2.init(batches[0])
    step = trainer2.jit_train_step(batches[0], state2)
    losses = []
    for b in batches:
        state2, m = step(state2, b)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses,
                               rtol=0, atol=0)
    (name, spec), = model.ps_specs().items()
    assert sm.tables[name].weights.shape[1] == spec.output_dim
    np.testing.assert_array_equal(np.asarray(sm.tables[name].weights),
                                  np.asarray(state2.tables[name].weights))
    for k, v in state2.tables[name].slots.items():
        np.testing.assert_array_equal(np.asarray(sm.tables[name].slots[k]),
                                      np.asarray(v))


def test_mesh_train_many_packed_hash(tmp_path):
    """Hash tables on the mesh pack too (probe/insert/overflow unchanged);
    checkpoint saved from the post-scan state restores identically."""
    from openembedding_tpu.embedding import Embedding
    from openembedding_tpu.model import EmbeddingModel
    from openembedding_tpu.models.ctr import LogisticRegression
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    steps = 3
    model = EmbeddingModel(
        module=LogisticRegression(),
        embeddings=[Embedding(input_dim=-1, output_dim=8, name="categorical",
                              capacity=2048)])
    mesh = make_mesh()
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.1), mesh=mesh)
    rng = np.random.default_rng(17)
    batches = [{"sparse": {"categorical": rng.integers(0, 100_000, (32, 4))
                           .astype(np.int64)},
                "dense": None,
                "label": rng.integers(0, 2, (32,)).astype(np.float32)}
               for _ in range(steps)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs) if xs[0] is not None else None, *batches,
        is_leaf=lambda x: x is None)

    state = trainer.init(batches[0])
    many = trainer.jit_train_many(stacked, state)
    sm, metrics = many(state, stacked)
    assert np.isfinite(np.asarray(metrics["loss"])).all()

    trainer2 = MeshTrainer(model, embed.Adagrad(learning_rate=0.1), mesh=mesh)
    state2 = trainer2.init(batches[0])
    step = trainer2.jit_train_step(batches[0], state2)
    for b in batches:
        state2, m = step(state2, b)
    np.testing.assert_array_equal(
        np.asarray(sm.tables["categorical"].keys),
        np.asarray(state2.tables["categorical"].keys))
    np.testing.assert_array_equal(
        np.asarray(sm.tables["categorical"].weights),
        np.asarray(state2.tables["categorical"].weights))

    # post-scan state checkpoints in the normal split format; compare via
    # eval (host-side key re-insertion may place rows in different slots —
    # slot positions are an implementation detail, lookups are the contract)
    ck = str(tmp_path / "ck")
    trainer.save(sm, ck)
    state3 = trainer.load(trainer.init(batches[0]), ck)
    ev = trainer.jit_eval_step(batches[0], sm)
    a = np.asarray(ev(sm, batches[0])["logits"])
    c = np.asarray(ev(state3, batches[0])["logits"])
    np.testing.assert_array_equal(a, c)


def test_train_many_unpackable_still_works():
    """A packed width in XLA's padded-copy regime (32 < W < 128) bypasses
    packing; train_many still runs on the split layout."""
    V, steps = 512, 3
    # dim 33 -> table width 34 (folded first-order col), +34 accum = 68: gated
    model = make_deepfm(vocabulary=V, dim=33)
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batches = list(synthetic_criteo(32, id_space=V, steps=steps, seed=9))
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
    state = trainer.init(batches[0])
    assert trainer._packed_layouts(state) == {}
    sm, metrics = trainer.jit_train_many()(state, stacked)
    assert np.isfinite(np.asarray(metrics["loss"])).all()


def _count_table_scatters(txt, shape):
    """Scatters producing a f32[shape] table, across XLA lowerings: the
    native `scatter(` op, or (CPU backends that expand scatter) a `while`
    loop carrying the table whose metadata records the originating scatter."""
    import re

    direct = re.findall(rf"= f32\[{shape}\]\S* scatter\(", txt)
    lowered = [l for l in txt.splitlines()
               if re.search(rf"%while\.\d+ = \(s32\[\], f32\[{shape}\]", l)
               and "/scatter" in l]
    return len(direct) + len(lowered)


def test_packed_scan_compiles_one_scatter_per_table():
    """Structural pin on the packed win: the compiled train_many updates the
    table through ONE scatter into the packed (V, 20) array — never the two
    split-layout scatters ((V, 10) weights + (V, 10) accum) — and temps stay
    far below a second table copy. HLO-shape matching is deliberately narrow;
    if an XLA upgrade reshuffles instruction names, update the patterns, but
    a reappearing split-shape scatter or a table-sized temp is a real
    regression."""
    V = 1 << 18
    model = make_deepfm(vocabulary=V, dim=9)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batches = list(synthetic_criteo(256, id_space=V, steps=2, seed=1))
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
    state = tr.init(batches[0])
    compiled = jax.jit(tr.train_many, donate_argnums=(0,)).lower(
        state, stacked).compile()

    txt = compiled.as_text()
    packed = _count_table_scatters(txt, f"{V},20")
    split = _count_table_scatters(txt, f"{V},10")
    assert packed == 1, f"expected 1 packed-table scatter, found {packed}"
    assert split == 0, f"split-layout scatters reappeared: {split}"

    ma = compiled.memory_analysis()
    if ma is not None:  # backend-dependent
        packed_bytes = V * 20 * 4
        assert ma.temp_size_in_bytes < 3 * packed_bytes, (
            f"temps {ma.temp_size_in_bytes} suggest an extra table copy "
            f"inside the scan (packed table is {packed_bytes})")


def test_packed_scan_dim64_split_first_order_one_scatter_each():
    """The dim-64 benchmark configuration (VERDICT r3 weak #4): split
    first-order auto-engages at lane-multiple dims, so train_many packs BOTH
    tables — categorical 64+64 -> (V, 128) lane-exact, first_order 1+1 ->
    (V, 2) sublane — and each updates through ONE packed scatter with no
    split-shape scatters left. The on-chip HBM claim (no 128-lane-padded temp
    copy of the table at width 128) is probed by `tools/dim64_probe.py` on
    real TPU; this pins the program STRUCTURE on any backend."""
    V = 1 << 14
    model = make_deepfm(vocabulary=V, dim=64)
    assert set(model.specs) == {"categorical", "first_order"}
    tr = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batches = list(synthetic_criteo(256, id_space=V, steps=2, seed=1))
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
    state = tr.init(batches[0])
    assert set(tr._packed_layouts(state)) == {"categorical", "first_order"}
    compiled = jax.jit(tr.train_many, donate_argnums=(0,)).lower(
        state, stacked).compile()

    txt = compiled.as_text()
    cat = _count_table_scatters(txt, f"{V},128")
    fo = _count_table_scatters(txt, f"{V},2")
    split = (_count_table_scatters(txt, f"{V},64")
             + _count_table_scatters(txt, f"{V},65")
             + _count_table_scatters(txt, f"{V},1"))
    assert cat == 1, f"expected 1 packed categorical scatter, found {cat}"
    assert fo == 1, f"expected 1 packed first-order scatter, found {fo}"
    assert split == 0, f"split-layout scatters reappeared: {split}"


def test_seq_mesh_train_many_packed_matches_step_loop():
    """SeqMeshTrainer (context parallelism) inherits the packed scan hooks:
    a SASRec with a packable item table (dim 16 + Adagrad accum = 32) runs
    jit_train_many on the packed per-shard layout and matches the per-step
    split path exactly on the same (data, seq) mesh."""
    from jax.sharding import Mesh
    from openembedding_tpu.models import make_sasrec, synthetic_sequences
    from openembedding_tpu.parallel import SeqMeshTrainer

    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devices, ("data", "seq"))
    steps = 3

    def build():
        model = make_sasrec(512, 16, attention="ring")
        return model, SeqMeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                                     mesh=mesh)

    batches = list(synthetic_sequences(8, 16, 512, steps=steps, seed=21))
    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)

    model, tr = build()
    state = tr.init(batches[0])
    assert tr._packed_layouts(state), "expected the item table to pack"
    many = tr.jit_train_many(stacked, state)
    sm, metrics = many(state, stacked)

    model2, tr2 = build()
    state2 = tr2.init(batches[0])
    step = tr2.jit_train_step(batches[0], state2)
    losses = []
    for b in batches:
        state2, m = step(state2, b)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses,
                               rtol=0, atol=0)
    for name in model.ps_specs():
        np.testing.assert_array_equal(
            np.asarray(sm.tables[name].weights),
            np.asarray(state2.tables[name].weights))
