"""Line-rate ingest (round 20): per-host file-sharded reading must be
bit-identical to the single-global-reader control (file by file — the
no-shuffle-barrier guarantee), the depth-D feed ring bit-identical to the
depth-1 synchronous path, the parse pool's reorder stage deterministic under
adversarial worker delays, and every early-exit path must join every thread
(the round-19 leak class). Plus the measured attribution lane: input waits
land in `trainer.input_wait_ms` and `input_wait_share` folds them against
step time the way tools/ingest_slo.json gates."""

import threading
import time

import numpy as np
import pytest

import jax

import openembedding_tpu as embed
from openembedding_tpu.data import criteo, ingest
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.parallel import MeshTrainer, make_mesh
from openembedding_tpu.utils import metrics, stepwatch

VOCAB = 1 << 10


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics._REGISTRY.clear()
    yield
    metrics._REGISTRY.clear()


def _tsv_files(tmp_path, rows=(10, 7, 12, 9, 11)):
    """A small day-file set: varying row counts so per-file partial tails
    (dropped on both paths) are exercised, not dodged."""
    paths = []
    for fi, n in enumerate(rows):
        lines = []
        for r in range(n):
            label = str((fi + r) % 2)
            dense = [str(fi * 100 + r + d) for d in range(13)]
            cats = [format(fi * 10007 + r * 31 + c, "x") for c in range(26)]
            lines.append("\t".join([label] + dense + cats))
        p = tmp_path / f"day_{fi}.tsv"
        p.write_text("\n".join(lines) + "\n")
        paths.append(str(p))
    return paths


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x["label"]),
                                      np.asarray(y["label"]))
        np.testing.assert_array_equal(np.asarray(x["dense"]),
                                      np.asarray(y["dense"]))
        np.testing.assert_array_equal(
            np.asarray(x["sparse"]["categorical"]),
            np.asarray(y["sparse"]["categorical"]))


# -- per-host file sharding ---------------------------------------------------


def test_ring_shard_epoch_coverage_and_rotation():
    n_files, n_hosts = 11, 4
    for epoch in range(n_hosts + 1):
        sets = [ingest.ring_shard(n_files, h, n_hosts, epoch)
                for h in range(n_hosts)]
        # every epoch covers every file exactly once across hosts
        union = sorted(i for s in sets for i in s)
        assert union == list(range(n_files)), (epoch, union)
        # ring rotation: host h's files in epoch e are host (h+1)%N's in e+1
        for h in range(n_hosts):
            assert (ingest.ring_shard(n_files, h, n_hosts, epoch)
                    == ingest.ring_shard(n_files, (h + 1) % n_hosts,
                                         n_hosts, epoch + 1))
    # a host reads EVERY file once over num_hosts epochs
    over_epochs = sorted(i for e in range(n_hosts)
                         for i in ingest.ring_shard(n_files, 0, n_hosts, e))
    assert over_epochs == list(range(n_files))


def test_sharded_files_epoch_major_order():
    got = list(ingest.sharded_files(["a", "b", "c"], host_id=0, num_hosts=2,
                                    epochs=2))
    assert got == [(0, 0, "a"), (0, 2, "c"), (1, 1, "b")]


def test_sharded_reader_union_bit_identical_to_global(tmp_path):
    """The acceptance pin: per-host sharded reading is bit-identical to the
    global-reader control. Per file, each host's stream must equal the
    control's stream for that file, and the hosts' file sets partition the
    set — batches never span files, so the union IS the control."""
    paths = _tsv_files(tmp_path)
    n_hosts = 3
    kw = dict(source="tsv", epochs=1, native="off", id_space=VOCAB)

    def per_file_control(path):
        return list(ingest.sharded_reader([path], 4, host_id=0, num_hosts=1,
                                          **kw))

    control = {p: per_file_control(p) for p in paths}
    covered = []
    for h in range(n_hosts):
        mine = ingest.ring_shard(len(paths), h, n_hosts)
        covered.extend(mine)
        got = list(ingest.sharded_reader(paths, 4, host_id=h,
                                         num_hosts=n_hosts, **kw))
        want = [b for i in mine for b in control[paths[i]]]
        _assert_batches_equal(got, want)
    assert sorted(covered) == list(range(len(paths)))
    # and the num_hosts=1 "union" control is exactly the per-file concat
    whole = list(ingest.sharded_reader(paths, 4, host_id=0, num_hosts=1,
                                       **kw))
    _assert_batches_equal(whole, [b for p in paths for b in control[p]])


def test_parse_pool_reader_bit_identical_to_inline(tmp_path):
    paths = _tsv_files(tmp_path)
    kw = dict(source="tsv", epochs=2, native="off", id_space=VOCAB)
    inline = list(ingest.sharded_reader(paths, 4, host_id=0, num_hosts=2,
                                        workers=0, **kw))
    pooled = list(ingest.sharded_reader(paths, 4, host_id=0, num_hosts=2,
                                        workers=3, **kw))
    _assert_batches_equal(pooled, inline)


# -- ParsePool reorder stage --------------------------------------------------


def test_parse_pool_order_deterministic_under_adversarial_delays():
    delays = {0: 0.02, 1: 0.0, 2: 0.015, 3: 0.001, 4: 0.01, 5: 0.0}

    def parse(task):
        time.sleep(delays[task])  # make workers finish far out of order
        return task * 10

    for workers in (1, 2, 4):
        with ingest.ParsePool(range(6), parse, workers=workers) as pool:
            assert list(pool) == [0, 10, 20, 30, 40, 50], f"{workers=}"


def test_parse_pool_fault_surfaces_at_sequence_position():
    def parse(task):
        if task == 3:
            raise RuntimeError("bad file")
        time.sleep(0.002 if task % 2 else 0.0)
        return task

    pool = ingest.ParsePool(range(6), parse, workers=3)
    got = []
    with pytest.raises(RuntimeError, match="bad file"):
        for p in pool:
            got.append(p)
    assert got == [0, 1, 2]  # everything before the bad task, in order
    with pool._lock:
        assert pool._dispatcher is None and not pool._workers


def test_parse_pool_early_exit_joins_every_worker():
    before = {t.ident for t in threading.enumerate()}
    pool = ingest.ParsePool(range(50), lambda t: t, workers=4)
    it = iter(pool)
    assert next(it) == 0
    pool.close()
    pool.close()  # idempotent
    with pool._lock:
        assert pool._dispatcher is None and not pool._workers
    leaked = [t for t in threading.enumerate()
              if t.ident not in before and t.name.startswith("ingest-")]
    assert not leaked, f"leaked threads: {leaked}"


# -- FeedRing -----------------------------------------------------------------


def _host_batches(steps=8, bs=16, seed=0):
    return list(criteo.synthetic_criteo(bs, id_space=VOCAB, steps=steps,
                                        seed=seed))


@pytest.mark.parametrize("depth", [2, 4])
def test_feed_ring_bit_identical_to_depth1(depth):
    src = _host_batches()
    d1 = list(ingest.FeedRing(iter(src), depth=1, device=False, label="d1"))
    dd = list(ingest.FeedRing(iter(src), depth=depth, device=False,
                              label=f"d{depth}"))
    _assert_batches_equal(dd, d1)


def test_feed_ring_device_mode_bit_identical():
    mesh = make_mesh(jax.devices()[:4])
    src = _host_batches(steps=4)
    with ingest.FeedRing(iter(src), depth=3, mesh=mesh,
                         label="dev") as ring:
        got = list(ring)
    assert len(got) == len(src)
    for host, dev in zip(src, got):
        assert isinstance(dev["dense"], jax.Array)
        np.testing.assert_array_equal(np.asarray(dev["dense"]),
                                      host["dense"])
        np.testing.assert_array_equal(
            np.asarray(dev["sparse"]["categorical"]),
            host["sparse"]["categorical"])


def test_feed_ring_window_mode_stacks_and_drops_tail():
    src = _host_batches(steps=7)
    ring = ingest.FeedRing(iter(src), depth=2, device=False, window=3,
                           label="win")
    ws = list(ring)
    assert len(ws) == 2  # 7 batches -> 2 windows of 3, tail of 1 dropped
    assert ws[0]["dense"].shape == (3,) + src[0]["dense"].shape
    np.testing.assert_array_equal(ws[1]["dense"][0], src[3]["dense"])
    snap = metrics.Accumulator.get("ingest.dropped", "sum",
                                   labels={"ring": "win"})
    assert snap.value() == 1.0


def test_window_batch_sharding():
    mesh = make_mesh(jax.devices()[:4])
    src = _host_batches(steps=4, bs=8)
    ring = ingest.FeedRing(iter(src), depth=2, mesh=mesh, window=2,
                           label="wb")
    ws = list(ring)
    assert len(ws) == 2
    w = ws[0]
    assert w["dense"].shape == (2, 8, 13)
    # leading K replicated, batch dim sharded: each device holds all K steps
    # of its batch slice
    db = w["dense"].addressable_shards[0].data.shape
    assert db[0] == 2 and db[1] == 2  # K intact, batch 8/4 devices


def test_feed_ring_early_exit_joins_producer_and_counts_drops():
    src = _host_batches(steps=12)
    ring = ingest.FeedRing(iter(src), depth=4, device=False, label="early")
    next(ring)
    time.sleep(0.05)  # let the producer fill the ring
    ring.close()
    ring.close()  # idempotent
    with ring._lock:
        assert ring._thread is None
    acc = metrics.Accumulator.get("ingest.dropped", "sum",
                                  labels={"ring": "early"})
    assert acc.value() >= 1.0  # staged-but-undelivered batches were counted


def test_feed_ring_propagates_source_exception():
    def bad():
        yield _host_batches(steps=1)[0]
        raise ValueError("source died")

    ring = ingest.FeedRing(bad(), depth=2, device=False, label="bad")
    next(ring)
    with pytest.raises(ValueError, match="source died"):
        next(ring)
    with ring._lock:
        assert ring._thread is None


def test_feed_ring_publishes_throughput_telemetry():
    src = _host_batches(steps=8, bs=16)
    list(ingest.FeedRing(iter(src), depth=2, device=False, label="tel",
                         rate_every=4))
    rep = metrics.report()
    assert rep['ingest.examples_per_sec{ring="tel"}'] > 0
    assert rep['ingest.bytes_per_sec{ring="tel"}'] > 0
    assert 'ingest.queue_depth{ring="tel"}' in rep
    assert 'ingest.slot_fill{ring="tel",slot="0"}' in rep


# -- prefetch_to_device telemetry (the round-19 producer, now observable) -----


def test_prefetch_telemetry_and_early_exit_drop_count():
    src = _host_batches(steps=6)
    it = criteo.prefetch_to_device(iter(src), size=3)
    next(it)
    time.sleep(0.05)  # producer fills the queue, then stalls on it
    it.close()
    rep = metrics.report()
    assert 'ingest.queue_depth{ring="prefetch"}' in rep
    assert rep.get('ingest.producer_stall_ms{ring="prefetch"}', 0.0) > 0.0
    assert rep.get('ingest.dropped{ring="prefetch"}', 0.0) >= 1.0


# -- the measured input-wait attribution lane ---------------------------------


def test_timed_batches_records_input_wait():
    def slow():
        for b in _host_batches(steps=3):
            time.sleep(0.01)
            yield b

    got = list(stepwatch.timed_batches(slow()))
    assert len(got) == 3
    acc = metrics.Accumulator.get("trainer.input_wait_ms", "hist")
    assert acc.count == 3
    assert acc.value() >= 5.0  # mean wait reflects the 10ms source stalls


def test_input_wait_share_folds_lanes():
    assert ingest.input_wait_share() is None  # no lanes yet -> no verdict
    for _ in range(4):
        metrics.observe("trainer.input_wait_ms", 1.0, "hist")
        metrics.observe("trainer.window_ms", 19.0, "hist")
    share = ingest.input_wait_share()
    assert share == pytest.approx(0.05)
    assert metrics.report()["ingest.input_wait_share"] == pytest.approx(0.05)


def test_train_stream_compute_bound_vs_throttled():
    """The soak's attribution pin, miniature: fed at line rate the stream is
    compute-bound (input-wait share ~0); with a deliberately throttled
    producer the SAME loop is attributed input-bound."""
    mesh = make_mesh(jax.devices()[:4])
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=mesh,
                     seed=1, wire="fp32")
    src = _host_batches(steps=6, bs=16)
    sample = jax.tree_util.tree_map(np.asarray, src[0])
    state = tr.init(sample)

    ring = ingest.FeedRing(iter(src), depth=3, mesh=mesh, window=2,
                           label="fast")
    state, rep = tr.train_stream(state, ring)
    assert rep["windows"] == 3
    assert np.isfinite(rep["loss"])
    fast_share = ingest.input_wait_share()
    assert fast_share is not None and fast_share < 0.5

    metrics._REGISTRY.clear()
    throttled = ingest.FeedRing(iter(src), depth=1, mesh=mesh, window=2,
                                label="slow", throttle_s=0.05)
    state, rep = tr.train_stream(state, throttled)
    assert rep["windows"] == 3
    slow_share = ingest.input_wait_share()
    assert slow_share is not None and slow_share > 0.5, \
        f"throttled producer not attributed input-bound: {slow_share}"


def test_feed_end_to_end_synthetic(tmp_path):
    """feed() composes reader -> pool -> ring; synthetic spec files shard
    like real days and the stream is bit-identical across worker counts."""
    files = [f"synthetic://steps=4&seed={s}&id_space={VOCAB}"
             for s in range(3)]
    a = list(ingest.feed(files, 8, source="synthetic", depth=2, workers=0,
                         device=False, label="fa"))
    b = list(ingest.feed(files, 8, source="synthetic", depth=3, workers=2,
                         device=False, label="fb"))
    assert len(a) == 12
    _assert_batches_equal(b, a)
