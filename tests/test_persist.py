"""Async persistence (PMem-equivalent) tests: commit protocol, crash consistency,
pending-window backpressure, policy, restore (reference: `pmem_c_api_test.cpp`,
`pmem_embedding_table_test.cpp`, AutoPersist in `test/benchmark/criteo_deepctr.py`)."""

import os
import shutil
import time

import jax
import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.persist import (AsyncPersister, PersistPolicy,
                                       latest_persist, list_persists,
                                       restore_server_model)

VOCAB = 1 << 10


@pytest.fixture()
def setup():
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=6, seed=1))
    state = trainer.init(batches[0])
    return model, trainer, state, batches


def test_policy_steps_and_seconds():
    p = PersistPolicy(every_steps=10)
    assert not p.should_persist(5)
    assert p.should_persist(10)
    p.mark(10)
    assert not p.should_persist(15)
    assert p.should_persist(20)
    pt = PersistPolicy(every_seconds=0.05)
    assert not pt.should_persist(1)
    time.sleep(0.06)
    assert pt.should_persist(1)
    with pytest.raises(ValueError):
        PersistPolicy()


def test_persist_restore_round_trip(setup, tmp_path):
    model, trainer, state, batches = setup
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    with AsyncPersister(trainer, model, root, window=2, keep=10,
                        policy=PersistPolicy(every_steps=2)) as p:
        persisted_steps = []
        for b in batches:
            state, _ = step(state, b)
            if p.maybe_persist(state):
                persisted_steps.append(int(state.step))
        p.wait()
        expect_w = np.asarray(state.tables["categorical"].weights)
    assert persisted_steps == [2, 4, 6]
    assert [s for s, _ in list_persists(root)] == [2, 4, 6]

    fresh = trainer.init(batches[0])
    restored = restore_server_model(fresh, model, root, trainer=trainer)
    assert int(restored.step) == 6
    np.testing.assert_array_equal(
        np.asarray(restored.tables["categorical"].weights), expect_w)


def test_uncommitted_persist_ignored(setup, tmp_path):
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    state, _ = step(state, batches[0])
    with AsyncPersister(trainer, model, root, window=1,
                        policy=PersistPolicy(every_steps=1)) as p:
        p.persist(state)
    # fake a crash mid-write: newer dir without COMMIT marker
    committed = latest_persist(root)
    crashed = os.path.join(root, "persist_000000000099")
    shutil.copytree(committed, crashed)
    os.unlink(os.path.join(crashed, "COMMIT"))
    assert latest_persist(root) == committed  # step 99 not eligible
    restored = restore_server_model(trainer.init(batches[0]), model, root,
                                    trainer=trainer)
    assert int(restored.step) == 1


def test_gc_keeps_last_k(setup, tmp_path):
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    with AsyncPersister(trainer, model, root, window=1, keep=2,
                        policy=PersistPolicy(every_steps=1)) as p:
        for b in batches[:5]:
            state, _ = step(state, b)
            p.persist(state)
            p.wait()  # serialize so gc sees each commit
    steps = [s for s, _ in list_persists(root)]
    assert steps == [4, 5]


def test_repersist_same_step_supersedes(setup, tmp_path):
    """A restarted run re-reaching a step must overwrite the old persist of that
    step (committed or crash-leftover), not die with ENOTEMPTY."""
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    state, _ = step(state, batches[0])
    for _ in range(2):  # second pass hits the existing committed persist_1 dir
        with AsyncPersister(trainer, model, root, window=1,
                            policy=PersistPolicy(every_steps=1)) as p:
            p.persist(state)
    assert [s for s, _ in list_persists(root)] == [1]
    restored = restore_server_model(trainer.init(batches[0]), model, root,
                                    trainer=trainer)
    assert int(restored.step) == 1


def test_restore_without_persist_raises(setup, tmp_path):
    model, trainer, state, _ = setup
    with pytest.raises(FileNotFoundError):
        restore_server_model(state, model, str(tmp_path / "empty"),
                             trainer=trainer)


def test_writer_error_propagates(setup, tmp_path):
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    state, _ = step(state, batches[0])
    p = AsyncPersister(trainer, model, root, window=1,
                       policy=PersistPolicy(every_steps=1))
    try:
        # poison the root: writer's os.replace onto a file must fail
        p.persist(state)
        p._q.join()
        target = os.path.join(root, "persist_000000000002")
        os.makedirs(os.path.dirname(target), exist_ok=True)
        state, _ = step(state, batches[1])
        with open(target, "w") as f:
            f.write("in the way")
        p.persist(state)
        p._q.join()
        with pytest.raises(RuntimeError, match="async persist failed"):
            p._raise_pending_error()
    finally:
        p._error = None
        p.close()


def test_snapshot_isolated_from_donation(setup, tmp_path):
    """persist() must copy to host before returning: the next step donates the
    state's buffers, and the async write must still see the OLD values."""
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    state, _ = step(state, batches[0])
    want = np.asarray(state.tables["categorical"].weights).copy()
    with AsyncPersister(trainer, model, root, window=2,
                        policy=PersistPolicy(every_steps=1)) as p:
        p.persist(state)
        for b in batches[1:]:  # donates + mutates the tables while write runs
            state, _ = step(state, b)
        p.wait()
    restored = restore_server_model(trainer.init(batches[0]), model, root,
                                    trainer=trainer)
    # the persist captured step-1 state, untouched by later steps
    assert int(restored.step) == 1
    np.testing.assert_array_equal(
        np.asarray(restored.tables["categorical"].weights), want)


# -- incremental (dirty-window) persistence ----------------------------------


def _state_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _dir_bytes(path):
    total = 0
    for dirpath, _, files in os.walk(path):
        for f in files:
            total += os.path.getsize(os.path.join(dirpath, f))
    return total


def test_incremental_restore_equals_live_state(setup, tmp_path):
    """base + delta replay == the live state, bit for bit (rows, slots, dense
    params, dense optimizer slots, step, model_version)."""
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    model, trainer, state, batches = setup
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2, keep=10,
                              policy=PersistPolicy(every_steps=2),
                              full_every=100) as p:
        for b in batches:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()
    # first persist is the full base; the rest are deltas
    assert [s for s, _ in list_persists(root)] == [2]
    assert [s for s, _ in list_deltas(root)] == [4, 6]

    fresh = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    fstate = fresh.init(batches[0])
    fstate = restore_server_model(fstate, model, root, trainer=fresh)
    _state_equal(fstate, state)


def test_incremental_bytes_proportional_to_touched(tmp_path):
    """The VERDICT's acceptance: delta bytes scale with TOUCHED rows, not the
    table. A 2^16-row table trained on batches touching ~64 ids must produce
    deltas orders of magnitude smaller than the full base persist."""
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    big_vocab = 1 << 16
    model = make_deepfm(vocabulary=big_vocab, dim=4, hidden=(8,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    # every batch draws from a 64-id hot set: the dirty window stays tiny
    rng = np.random.default_rng(7)
    hot = rng.integers(0, big_vocab, size=64)
    batches = []
    for i in range(4):
        ids = hot[rng.integers(0, 64, size=(16, 26))].astype(np.int32)
        batches.append({"sparse": {"categorical": ids},
                        "label": rng.random(16).astype(np.float32)})
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2, keep=10,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        for b in batches:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()

    fulls = list_persists(root)
    deltas = list_deltas(root)
    assert len(fulls) == 1 and len(deltas) == 3
    full_bytes = _dir_bytes(fulls[0][1])
    for _, dpath in deltas:
        dbytes = _dir_bytes(dpath)
        # 64 rows x (4 weights + 4 slots + id) vs 2^16 rows: >100x smaller
        assert dbytes * 100 < full_bytes, (dbytes, full_bytes)

    fresh = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    fstate = fresh.init(batches[0])
    fstate = restore_server_model(fstate, model, root, trainer=fresh)
    _state_equal(fstate, state)


def test_incremental_uncommitted_delta_ignored(setup, tmp_path):
    """Crash consistency down the chain: a delta without COMMIT (and anything
    after it) is not replayed — restore lands on the last consistent prefix."""
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    model, trainer, state, batches = setup
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    states = {}
    with IncrementalPersister(trainer, model, root, window=2, keep=10,
                              policy=PersistPolicy(every_steps=2),
                              full_every=100) as p:
        for b in batches:
            state, _ = step(state, b)
            if p.maybe_persist(state, batch=b):
                p.wait()
                states[int(state.step)] = jax.device_get(state)
    # simulate a crash mid-write of the last delta: drop its COMMIT
    last_step, last_path = list_deltas(root)[-1]
    os.remove(os.path.join(last_path, "COMMIT"))

    fresh = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    fstate = fresh.init(batches[0])
    fstate = restore_server_model(fstate, model, root, trainer=fresh)
    assert int(fstate.step) == 4  # the consistent prefix: base(2) + delta(4)
    _state_equal(fstate, states[4])


def test_incremental_full_every_and_gc(setup, tmp_path):
    """A scheduled full persist supersedes the chain: older deltas are GC'd,
    restore uses the new base alone."""
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    model, trainer, state, batches = setup
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2, keep=10,
                              policy=PersistPolicy(every_steps=1),
                              full_every=2) as p:
        for b in batches:  # persists at steps 1..6; fulls at 1, 4 (2 deltas each)
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()
    full_steps = [s for s, _ in list_persists(root)]
    delta_steps = [s for s, _ in list_deltas(root)]
    assert full_steps[-1] == 4
    assert all(d > 4 for d in delta_steps), (full_steps, delta_steps)

    fresh = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    fstate = fresh.init(batches[0])
    fstate = restore_server_model(fstate, model, root, trainer=fresh)
    assert int(fstate.step) == 6
    _state_equal(fstate, jax.device_get(state))


def test_incremental_unobserved_window_falls_back_to_full(setup, tmp_path):
    """Steps advancing without observe() must NOT silently persist stale
    deltas: warn + full persist."""
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    model, trainer, state, batches = setup
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])  # full base
        state, _ = step(state, batches[1])
        with pytest.warns(RuntimeWarning, match="observed"):
            p.maybe_persist(state)  # no batch, no observe -> full + warning
        p.wait()
    assert [s for s, _ in list_persists(root)] == [1, 2]
    assert list_deltas(root) == []


def test_incremental_pair_keys_x64_off(tmp_path):
    """The dirty window under the default config (x64 off, split-pair hash
    keys): tracker ids are int64 host-side, the row reader/writer speak the
    pair layout."""
    from openembedding_tpu.persist import IncrementalPersister, list_deltas
    from openembedding_tpu.initializers import Constant
    import dataclasses

    with jax.enable_x64(False):
        model = make_deepfm(vocabulary=-1, dim=4, hidden=(8,), hashed=True,
                            capacity=4096)
        model.specs["categorical"] = dataclasses.replace(
            model.specs["categorical"], initializer=Constant(0.0))
        trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
        batches = list(synthetic_criteo(16, id_space=1 << 62, steps=4, seed=2,
                                        ids_dtype="pair"))
        state = trainer.init(batches[0])
        assert state.tables["categorical"].keys.ndim == 2
        step = trainer.jit_train_step()
        root = str(tmp_path / "persist")
        with IncrementalPersister(trainer, model, root, window=2,
                                  policy=PersistPolicy(every_steps=1),
                                  full_every=100) as p:
            for b in batches:
                state, _ = step(state, b)
                p.maybe_persist(state, batch=b)
            p.wait()
        assert len(list_deltas(root)) == 3

        fresh = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
        fstate = fresh.init(batches[0])
        fstate = restore_server_model(fstate, model, root, trainer=fresh)
        assert int(fstate.step) == 4
        # rows must match by id (slot layouts may differ between the restored
        # insert order and the live table's) — read through the model's pull
        from openembedding_tpu.embedding import lookup
        from openembedding_tpu.ops.id64 import np_ids_as_int64, np_split_ids
        ids = np.unique(np.concatenate(
            [np_ids_as_int64(b["sparse"]["categorical"]) for b in batches]))
        pair = jax.numpy.asarray(np_split_ids(ids))
        spec = model.specs["categorical"]
        np.testing.assert_array_equal(
            np.asarray(lookup(spec, fstate.tables["categorical"], pair)),
            np.asarray(lookup(spec, state.tables["categorical"], pair)))


def test_incremental_mesh_array_table(tmp_path):
    """Dirty-window persist on an 8-device mesh (array table): delta rows
    address through the shard-major layout, restore replays onto the sharded
    state bit-for-bit."""
    from openembedding_tpu.parallel import MeshTrainer, make_mesh
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=0,
                          mesh=make_mesh())
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=6, seed=1))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2, keep=10,
                              policy=PersistPolicy(every_steps=2),
                              full_every=100) as p:
        for b in batches:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()
    assert [s for s, _ in list_persists(root)] == [2]
    assert [s for s, _ in list_deltas(root)] == [4, 6]

    fresh = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=0,
                        mesh=make_mesh())
    fstate = fresh.init(batches[0])
    fstate = restore_server_model(fstate, model, root, trainer=fresh)
    _state_equal(fstate, state)
    # the restored state really trains (shardings intact)
    fstep = fresh.jit_train_step(batches[0], fstate)
    fstate, m = fstep(fstate, batches[0])
    assert np.isfinite(float(m["loss"]))


def test_incremental_mesh_hash_table(tmp_path):
    """Same on a HASHED model: per-shard probe for the touched-row read,
    sharded find-or-insert on replay. Rows must match by id (slot layouts
    may differ between live insertion order and replay order)."""
    import dataclasses
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from openembedding_tpu.initializers import Constant
    from openembedding_tpu.parallel import MeshTrainer, make_mesh
    from openembedding_tpu.parallel.sharded import sharded_lookup
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    def build():
        m = make_deepfm(vocabulary=-1, dim=4, hidden=(8,), hashed=True,
                        capacity=4096)
        m.specs["categorical"] = dataclasses.replace(
            m.specs["categorical"], initializer=Constant(0.0))
        return m

    model = build()
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=0,
                          mesh=make_mesh())
    batches = list(synthetic_criteo(16, id_space=1 << 40, steps=6, seed=2))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=2),
                              full_every=100) as p:
        for b in batches:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()
    assert len(list_deltas(root)) == 2

    fresh_model = build()
    fresh = MeshTrainer(fresh_model, embed.Adagrad(learning_rate=0.05),
                        seed=0, mesh=make_mesh())
    fstate = fresh.init(batches[0])
    fstate = restore_server_model(fstate, fresh_model, root, trainer=fresh)
    assert int(np.asarray(fstate.step)) == 6

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"].reshape(-1) for b in batches]))
    spec = model.specs["categorical"]

    def pull_rows(tr, st):
        pull = jax.jit(jax.shard_map(
            partial(sharded_lookup, spec, axis=tr.axis),
            mesh=tr.mesh,
            in_specs=(tr._table_pspec(spec), P()),
            out_specs=P(), check_vma=False))
        import jax.numpy as jnp
        return np.asarray(pull(st.tables["categorical"], jnp.asarray(ids)))

    np.testing.assert_array_equal(pull_rows(fresh, fstate),
                                  pull_rows(trainer, state))


def test_sharded_delta_restore_without_trainer(tmp_path):
    """Serving-side restore: a delta chain replays onto a SHARDED state with
    NO trainer in the process — the mesh/axis/pspecs are recovered from the
    state's own NamedShardings (`persist._StateMeshShim`), and the result is
    bit-identical to the trainer-driven restore. (Until round 5 this case
    raised; the reference restores per server node with no worker attached,
    `EmbeddingRestoreOperator.cpp:108-152`.)"""
    from openembedding_tpu.parallel import MeshTrainer, make_mesh
    from openembedding_tpu.persist import IncrementalPersister

    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=0,
                          mesh=make_mesh())
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=4, seed=3))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=2),
                              full_every=100) as p:
        for b in batches:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()

    fresh = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=0,
                        mesh=make_mesh())
    fstate = fresh.init(batches[0])
    fstate = restore_server_model(fstate, model, root)  # trainer omitted
    _state_equal(fstate, state)
    oracle = restore_server_model(
        MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=0,
                    mesh=make_mesh()).init(batches[0]),
        model, root, trainer=trainer)
    _state_equal(fstate, oracle)


def test_shard_row_reader_matches_direct_read(tmp_path):
    """`_make_shard_row_reader` (the multi-process delta read: per-shard
    outputs, no cross-shard psum) must agree with the replicated-output
    mesh reader on the same table — every touched row found exactly once,
    in the shard that owns it."""
    from openembedding_tpu.parallel import MeshTrainer, make_mesh
    from openembedding_tpu.persist import (_make_mesh_row_reader,
                                           _make_shard_row_reader)

    model = make_deepfm(vocabulary=-1, dim=4, hidden=(8,), hashed=True,
                        capacity=4096)
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=0,
                          mesh=make_mesh())
    batches = list(synthetic_criteo(16, id_space=1 << 40, steps=3, seed=4))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    for b in batches:
        state, _ = step(state, b)
    spec = model.specs["categorical"]
    ts = state.tables["categorical"]

    ids64 = np.unique(np.concatenate(
        [b["sparse"]["categorical"].reshape(-1) for b in batches]))
    n = ids64.size
    padded = 1 << (n - 1).bit_length()
    ids_h = np.concatenate([ids64, np.full((padded - n,), -1, np.int64)])
    ids_dev = ids_h.astype(ts.keys.dtype) if ts.keys.ndim == 1 else None
    if ids_dev is None:
        from openembedding_tpu.ops.id64 import np_split_ids
        ids_dev = np_split_ids(ids_h)

    pspec = trainer._table_pspec(spec)
    found_r, w_r, s_r = _make_mesh_row_reader(
        trainer.mesh, trainer.axis, pspec)(ts, ids_dev)
    found_s, w_s, s_s = _make_shard_row_reader(
        trainer.mesh, trainer.axis, pspec, True, spec.input_dim)(ts, ids_dev)

    S = trainer.num_shards
    fs = np.asarray(found_s).reshape(S, padded)
    ws = np.asarray(w_s).reshape(S, padded, -1)
    assert (fs.sum(axis=0) <= 1).all(), "an id found in more than one shard"
    np.testing.assert_array_equal(fs.any(axis=0), np.asarray(found_r))
    np.testing.assert_array_equal(ws.sum(axis=0), np.asarray(w_r))
    for k in s_r:
        np.testing.assert_array_equal(
            np.asarray(s_s[k]).reshape(S, padded, -1).sum(axis=0),
            np.asarray(s_r[k]))


def test_dirty_tracker_applies_batch_transform():
    """A model with `batch_transform` (shared-Embedding Keras conversions)
    synthesizes its table feature inside jit; the HOST-side tracker must run
    the same transform or its feature lookup KeyErrors (round-5 review
    regression)."""
    import jax.numpy as jnp

    from openembedding_tpu.persist import DirtyTracker

    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    feat = model.specs["categorical"].feature_name

    def transform(batch, _feat=feat):
        sp = dict(batch["sparse"])
        sp[_feat] = jnp.concatenate(
            [jnp.asarray(sp["site_a"]), jnp.asarray(sp["site_b"])], axis=1)
        return {**batch, "sparse": sp}

    model.batch_transform = transform
    tracker = DirtyTracker(model)
    batch = {"sparse": {"site_a": np.array([[1, 2]], np.int64),
                        "site_b": np.array([[3, 2, 7]], np.int64)},
             "dense": None, "label": np.zeros((1,), np.float32)}
    tracker.observe(batch)
    ids = tracker.take()["categorical"]
    np.testing.assert_array_equal(ids, [1, 2, 3, 7])


def test_dirty_tracker_window_semantics():
    """observe() accumulates per-batch uniques cheaply; take() returns the
    sorted cross-batch union and resets the window."""
    from openembedding_tpu.persist import DirtyTracker

    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    t = DirtyTracker(model)
    t.observe({"sparse": {"categorical": np.asarray([[5, 3], [9, 5]])}})
    t.observe({"sparse": {"categorical": np.asarray([[3, -1], [7, 7]])}})
    got = t.take()
    np.testing.assert_array_equal(got["categorical"], [3, 5, 7, 9])  # no -1
    assert t.take()["categorical"].size == 0  # window reset


def test_superseded_delta_gc_opt_out(setup, tmp_path):
    """`prune_deltas=False` keeps deltas a newer full has superseded — the
    retention opt-out for sync publishers that serve history to slow
    subscribers; the default prunes them (long online runs must not leak one
    directory per persist interval)."""
    from openembedding_tpu.persist import IncrementalPersister, list_deltas

    model, trainer, _state, batches = setup
    step = trainer.jit_train_step()
    for prune, expect_old_deltas in ((True, False), (False, True)):
        root = str(tmp_path / f"persist_{prune}")
        with IncrementalPersister(trainer, model, root, window=2, keep=10,
                                  policy=PersistPolicy(every_steps=1),
                                  full_every=2,
                                  prune_deltas=prune) as p:
            s = trainer.init(batches[0])  # the step donates its input state
            for b in batches:  # fulls at 1, 4; deltas at 2, 3, 5, 6
                s, _ = step(s, b)
                p.maybe_persist(s, batch=b)
                p.wait()  # serialize so gc sees each commit
        newest_full = list_persists(root)[-1][0]
        assert newest_full == 4
        old = [d for d, _ in list_deltas(root) if d <= newest_full]
        assert bool(old) == expect_old_deltas, (prune, old)
        # either way the replayable chain restores to the newest state
        restored = restore_server_model(trainer.init(batches[0]), model,
                                        root, trainer=trainer)
        assert int(restored.step) == 6


def test_delta_chain_broken_link_replays_prefix(setup, tmp_path):
    """Deleting a MIDDLE delta breaks the parent chain: restore replays only
    the consistent prefix (base + first delta), never skipping a link."""
    import shutil
    from openembedding_tpu.persist import (IncrementalPersister, delta_chain,
                                           list_deltas)

    model, trainer, state, batches = setup
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        for b in batches[:4]:  # full base at 1, deltas at 2, 3, 4
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()
    deltas = list_deltas(root)
    assert [s for s, _ in deltas] == [2, 3, 4]
    shutil.rmtree(deltas[1][1])  # delta_3 vanishes

    base, chain = delta_chain(root)
    assert base is not None
    assert [os.path.basename(c) for c in chain] == ["delta_000000000002"]
    restored = restore_server_model(trainer.init(batches[0]), model, root,
                                    trainer=trainer)
    assert int(restored.step) == 2  # the consistent prefix, not 4
