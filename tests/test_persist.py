"""Async persistence (PMem-equivalent) tests: commit protocol, crash consistency,
pending-window backpressure, policy, restore (reference: `pmem_c_api_test.cpp`,
`pmem_embedding_table_test.cpp`, AutoPersist in `test/benchmark/criteo_deepctr.py`)."""

import os
import shutil
import time

import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.persist import (AsyncPersister, PersistPolicy,
                                       latest_persist, list_persists,
                                       restore_server_model)

VOCAB = 1 << 10


@pytest.fixture()
def setup():
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=6, seed=1))
    state = trainer.init(batches[0])
    return model, trainer, state, batches


def test_policy_steps_and_seconds():
    p = PersistPolicy(every_steps=10)
    assert not p.should_persist(5)
    assert p.should_persist(10)
    p.mark(10)
    assert not p.should_persist(15)
    assert p.should_persist(20)
    pt = PersistPolicy(every_seconds=0.05)
    assert not pt.should_persist(1)
    time.sleep(0.06)
    assert pt.should_persist(1)
    with pytest.raises(ValueError):
        PersistPolicy()


def test_persist_restore_round_trip(setup, tmp_path):
    model, trainer, state, batches = setup
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    with AsyncPersister(trainer, model, root, window=2, keep=10,
                        policy=PersistPolicy(every_steps=2)) as p:
        persisted_steps = []
        for b in batches:
            state, _ = step(state, b)
            if p.maybe_persist(state):
                persisted_steps.append(int(state.step))
        p.wait()
        expect_w = np.asarray(state.tables["categorical"].weights)
    assert persisted_steps == [2, 4, 6]
    assert [s for s, _ in list_persists(root)] == [2, 4, 6]

    fresh = trainer.init(batches[0])
    restored = restore_server_model(fresh, model, root, trainer=trainer)
    assert int(restored.step) == 6
    np.testing.assert_array_equal(
        np.asarray(restored.tables["categorical"].weights), expect_w)


def test_uncommitted_persist_ignored(setup, tmp_path):
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    state, _ = step(state, batches[0])
    with AsyncPersister(trainer, model, root, window=1,
                        policy=PersistPolicy(every_steps=1)) as p:
        p.persist(state)
    # fake a crash mid-write: newer dir without COMMIT marker
    committed = latest_persist(root)
    crashed = os.path.join(root, "persist_000000000099")
    shutil.copytree(committed, crashed)
    os.unlink(os.path.join(crashed, "COMMIT"))
    assert latest_persist(root) == committed  # step 99 not eligible
    restored = restore_server_model(trainer.init(batches[0]), model, root,
                                    trainer=trainer)
    assert int(restored.step) == 1


def test_gc_keeps_last_k(setup, tmp_path):
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    with AsyncPersister(trainer, model, root, window=1, keep=2,
                        policy=PersistPolicy(every_steps=1)) as p:
        for b in batches[:5]:
            state, _ = step(state, b)
            p.persist(state)
            p.wait()  # serialize so gc sees each commit
    steps = [s for s, _ in list_persists(root)]
    assert steps == [4, 5]


def test_repersist_same_step_supersedes(setup, tmp_path):
    """A restarted run re-reaching a step must overwrite the old persist of that
    step (committed or crash-leftover), not die with ENOTEMPTY."""
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    state, _ = step(state, batches[0])
    for _ in range(2):  # second pass hits the existing committed persist_1 dir
        with AsyncPersister(trainer, model, root, window=1,
                            policy=PersistPolicy(every_steps=1)) as p:
            p.persist(state)
    assert [s for s, _ in list_persists(root)] == [1]
    restored = restore_server_model(trainer.init(batches[0]), model, root,
                                    trainer=trainer)
    assert int(restored.step) == 1


def test_restore_without_persist_raises(setup, tmp_path):
    model, trainer, state, _ = setup
    with pytest.raises(FileNotFoundError):
        restore_server_model(state, model, str(tmp_path / "empty"),
                             trainer=trainer)


def test_writer_error_propagates(setup, tmp_path):
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    state, _ = step(state, batches[0])
    p = AsyncPersister(trainer, model, root, window=1,
                       policy=PersistPolicy(every_steps=1))
    try:
        # poison the root: writer's os.replace onto a file must fail
        p.persist(state)
        p._q.join()
        target = os.path.join(root, "persist_000000000002")
        os.makedirs(os.path.dirname(target), exist_ok=True)
        state, _ = step(state, batches[1])
        with open(target, "w") as f:
            f.write("in the way")
        p.persist(state)
        p._q.join()
        with pytest.raises(RuntimeError, match="async persist failed"):
            p._raise_pending_error()
    finally:
        p._error = None
        p.close()


def test_snapshot_isolated_from_donation(setup, tmp_path):
    """persist() must copy to host before returning: the next step donates the
    state's buffers, and the async write must still see the OLD values."""
    model, trainer, state, batches = setup
    root = str(tmp_path / "persist")
    step = trainer.jit_train_step()
    state, _ = step(state, batches[0])
    want = np.asarray(state.tables["categorical"].weights).copy()
    with AsyncPersister(trainer, model, root, window=2,
                        policy=PersistPolicy(every_steps=1)) as p:
        p.persist(state)
        for b in batches[1:]:  # donates + mutates the tables while write runs
            state, _ = step(state, b)
        p.wait()
    restored = restore_server_model(trainer.init(batches[0]), model, root,
                                    trainer=trainer)
    # the persist captured step-1 state, untouched by later steps
    assert int(restored.step) == 1
    np.testing.assert_array_equal(
        np.asarray(restored.tables["categorical"].weights), want)
