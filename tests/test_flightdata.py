"""Round-21 flight-data layer (ISSUE 18 acceptance): metric history rings
(bounded per-series memory, /historz queries, /statusz sparklines),
device-memory accounting (analytic byte model == measured arrays EXACTLY on
the 8-virtual-device CPU mesh; the preflight gate rejects over-budget
hot-cache attachment), and postmortem capsules (`NonFiniteError` and an SLO
breach edge each auto-emit a capsule bundling correlated flight events,
history rings, the memory model and the collective fingerprint; the bundle
round-trips through the offline renderer `tools/capsule_report.py`), plus
the PeriodicReporter JSONL size rotation boundary."""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import openembedding_tpu as oe
import tools.capsule_report as capsule_report
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.model import EmbeddingModel
from openembedding_tpu.parallel import MeshTrainer, make_mesh
from openembedding_tpu.utils import (capsule, guards, history, memwatch,
                                     metrics, slo, trace)

S = 8  # conftest forces 8 virtual CPU devices


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("OETPU_CAPSULE_DIR", raising=False)
    monkeypatch.delenv("OETPU_HBM_BUDGET", raising=False)

    def wipe():
        metrics._REGISTRY.clear()
        trace.RECORDER.clear()
        history.HISTORY.clear()
        memwatch.WATCH.clear()
        memwatch.WATCH.configure(None)
        memwatch.WATCH.__dict__.pop("_last_device_stats", None)
        capsule.configure(None)
    wipe()
    yield
    wipe()


class _Tower(nn.Module):
    """Two dim-8 tables (array + hash) -> logits (B,)."""

    @nn.compact
    def __call__(self, embedded, dense):
        bias = self.param("bias", nn.initializers.zeros, (1,), jnp.float32)
        out = (jnp.sum(embedded["a"].astype(jnp.float32), axis=(1, 2))
               + jnp.sum(embedded["b"].astype(jnp.float32), axis=(1, 2)))
        return out + bias[0]


def _model(vocab=256):
    return EmbeddingModel(_Tower(), [
        oe.Embedding(vocab, 8, name="a"),
        oe.Embedding(-1, 8, name="b", capacity=4096),
    ])


def _batch(rng, vocab=256):
    return {"sparse": {"a": rng.integers(0, vocab, (32, 4)).astype(np.int32),
                       "b": rng.integers(0, 1 << 40, (32, 3)).astype(np.int64)},
            "label": rng.integers(0, 2, (32,)).astype(np.float32)}


# -- history rings ------------------------------------------------------------


def test_ring_depth_eviction_window_and_prune():
    r = history.Ring(maxlen=4)
    for i in range(7):
        r.append(float(i), i * 10)
    assert len(r) == 4
    # depth bound evicted the oldest three
    assert [v for _ts, v in r.items()] == [30, 40, 50, 60]
    assert r.last() == (6.0, 60)
    # time-window read
    assert [v for _ts, v in r.window(now=6.0, window_s=1.5)] == [50, 60]
    # prune keeps the latest sample even when everything is stale
    r.prune_older(cutoff=100.0, keep=1)
    assert r.items() == [(6.0, 60)]


def test_sample_registry_records_series_and_caps_labels():
    h = history.MetricHistory(depth=3, label_cap=2)
    for t in ("a", "b", "c"):  # 3 label sets > cap of 2
        metrics.observe("exchange.shard_rows", 1.0, "gauge",
                        labels={"table": t})
    metrics.observe("train.steps", 1.0)
    for ts in (10.0, 11.0, 12.0, 13.0):
        h.sample_registry(ts=ts)
    series = h.query("exchange.shard_rows")
    assert len(series) == 2  # the third label set was capped, not recorded
    # depth bound: 4 samples into depth-3 rings keeps the newest 3
    assert all(len(s["points"]) == 3 for s in series)
    assert [p[0] for p in series[0]["points"]] == [11.0, 12.0, 13.0]
    # the drop is observable, not silent
    assert metrics.Accumulator.get("history.dropped_series").value() > 0
    # hist-kind accumulators store derived-stat dicts
    metrics.observe("serving.predict.ms", 5.0, "hist")
    h.sample_registry(ts=14.0)
    (hs,) = h.query("serving.predict.ms")
    assert set(hs["points"][-1][1]) == set(history.HIST_FIELDS)


def test_reporter_tick_feeds_history_and_sparklines_render():
    metrics.observe("train.steps", 1.0)
    rep = metrics.PeriodicReporter(interval=60, sink=lambda s: None)
    rep._tick()
    metrics.observe("train.steps", 2.0)
    rep._tick()
    (s,) = history.HISTORY.query("train.steps")
    assert [p[1] for p in s["points"]] == [1.0, 2.0]
    out = history.render_sparklines()
    assert "train.steps" in out and "n=2" in out


# -- serving surfaces: /historz, /statusz panels, POST /capsule ---------------


@pytest.fixture()
def server(tmp_path):
    from openembedding_tpu.serving import make_server
    srv = make_server(str(tmp_path / "reg"), port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def _get(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def test_historz_catalogue_series_and_window_queries(server):
    metrics.observe("ingest.examples_per_sec", 100.0, "gauge")
    metrics.observe("exchange.shard_rows", 7.0, "gauge",
                    labels={"table": "user"})
    history.HISTORY.sample_registry(ts=1000.0)
    metrics.observe("ingest.examples_per_sec", 200.0, "gauge")
    history.HISTORY.sample_registry(ts=2000.0)

    doc = _get(f"{server}/historz")
    assert "ingest.examples_per_sec" in doc["metrics"]
    doc = _get(f"{server}/historz?metric=ingest.examples_per_sec")
    (s,) = doc["series"]
    assert [p[1] for p in s["points"]] == [100.0, 200.0]
    # label filter
    doc = _get(f"{server}/historz?metric=exchange.shard_rows&table=user")
    assert len(doc["series"]) == 1
    doc = _get(f"{server}/historz?metric=exchange.shard_rows&table=nope")
    assert doc["series"] == []
    # bad window -> 400, not a 500
    req = urllib.request.Request(
        f"{server}/historz?metric=train.steps&window=bogus")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    assert ei.value.code == 400


def test_statusz_renders_ingest_history_and_memory_panels(server):
    metrics.observe("ingest.input_wait_share", 0.01, "gauge")
    history.HISTORY.sample_registry()
    memwatch.WATCH.set_component("feed_ring", 4096,
                                 labels={"ring": "train"})
    with urllib.request.urlopen(f"{server}/statusz") as r:
        body = r.read().decode()
    assert "-- ingest (line-rate) --" in body
    assert "ingest.input_wait_share" in body
    assert "-- metric history (GET /historz for JSON) --" in body
    assert "-- device memory (memwatch ledger) --" in body
    assert "feed_ring{ring=train}: 4,096B" in body


def test_post_capsule_endpoint(server, tmp_path):
    # not armed -> 409
    req = urllib.request.Request(f"{server}/capsule", data=b"{}",
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    assert ei.value.code == 409

    capsule.configure(str(tmp_path / "caps"))
    body = json.dumps({"reason": "operator_probe", "note": "drill"}).encode()
    req = urllib.request.Request(f"{server}/capsule", data=body,
                                 method="POST")
    with urllib.request.urlopen(req) as r:
        doc = json.loads(r.read())
    assert doc["reason"] == "operator_probe"
    assert os.path.exists(doc["path"])
    cap = capsule_report.load(doc["path"])
    assert cap["attrs"]["note"] == "drill"
    # the same reason inside the rate-limit window -> 429
    req = urllib.request.Request(f"{server}/capsule", data=body,
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    assert ei.value.code == 429


# -- postmortem capsules: the two auto-trigger acceptance paths ---------------


def _mesh_trainer(**kw):
    trainer = MeshTrainer(_model(), oe.Adagrad(learning_rate=0.05),
                          mesh=make_mesh(), wire="fp32", **kw)
    batch = _batch(np.random.default_rng(0))
    state = trainer.init(batch)
    return trainer, state, batch


def test_nonfinite_capsule_e2e_with_renderer_roundtrip(tmp_path):
    """THE acceptance pin: a planted NaN under halt_on_nonfinite emits one
    capsule carrying (a) the health/nonfinite flight event correlated to the
    failing request id, (b) >= 3 history series, (c) the memory model,
    (d) the collective fingerprint — and the capsule renders offline."""
    cap_dir = tmp_path / "caps"
    capsule.configure(str(cap_dir))
    trainer, state, batch = _mesh_trainer(halt_on_nonfinite=True)
    step = trainer.jit_train_step(batch, state)
    # the flight-data a real run would have accumulated by the failure:
    trainer.publish_memory(state)                       # memory ledger
    guards.collective_fingerprint(
        lambda x: jax.tree_util.tree_map(jnp.sum, x), batch["label"])
    for _ in range(2):                                   # >= 3 live series
        history.HISTORY.sample_registry()

    ts = state.tables["a"]
    state = state.replace(tables={
        **state.tables,
        "a": ts.replace(weights=ts.weights.at[:].set(np.nan))})
    with trace.request() as rid:
        state, mets = step(state, batch)
        with pytest.raises(oe.NonFiniteError):
            trainer.record_step_stats(mets)

    (path,) = cap_dir.glob("capsule-*-nonfinite.json.gz")
    cap = capsule_report.load(str(path))
    assert cap["reason"] == "nonfinite"
    assert "a" in cap["attrs"]["offenders"]
    # (a) correlated flight evidence: the nonfinite breadcrumb carries the
    # request id of the step that died
    evs = [e for e in cap["flight"]
           if e["kind"] == "event" and e["group"] == "health"
           and e["name"] == "nonfinite"]
    assert evs and evs[-1]["request_id"] == rid
    # (b) history rings rode along
    assert len(cap["history"]) >= 3
    # (c) the memory model names the table components
    comps = {(e["component"], e["labels"].get("table"))
             for e in cap["memory"]["components"]}
    assert ("table_weights", "a") in comps and ("table_weights", "b") in comps
    assert cap["memory"]["device_total_bytes"] > 0
    # (d) the collective fingerprint of the live program
    assert cap["fingerprint"] == guards.last_fingerprint()
    assert len(cap["fingerprint"]) == 16
    # offline renderer round-trip: header, flight, history, memory sections
    text = capsule_report.render(cap)
    assert "reason=nonfinite" in text
    assert "health/nonfinite" in text
    assert f"rid={rid}" in text
    assert "table_weights{table=a}" in text
    # request-filtered view keeps only the correlated items
    filtered = capsule_report.render(cap, request=rid)
    assert "health/nonfinite" in filtered


def test_slo_breach_edge_emits_capsule_once(tmp_path):
    cap_dir = tmp_path / "caps"
    capsule.configure(str(cap_dir))
    spec = slo.SLOSpec(name="numerics_cap", metric="health.nonfinite_total",
                       selector="value", op="==", threshold=0.0,
                       fast_window_s=0.0, slow_window_s=300.0,
                       burn_threshold=1e-9)
    ev = slo.SLOEvaluator([spec])
    metrics.observe("train.steps", 1.0)
    metrics.observe("ingest.examples", 10.0)
    metrics.observe("health.nonfinite_total", 0.0)
    history.HISTORY.sample_registry()
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.OK
    assert list(cap_dir.glob("capsule-*")) == []  # OK never emits

    metrics.observe("health.nonfinite_total", 3.0)
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.BREACHED
    (path,) = cap_dir.glob("capsule-*-slo_breach.json.gz")
    cap = capsule_report.load(str(path))
    assert cap["attrs"]["slo"] == "numerics_cap"
    assert cap["attrs"]["value"] == 3.0
    # still breached on the next round: edge-triggered, no second capsule
    (v,) = ev.evaluate_now()
    assert v["verdict"] == slo.BREACHED
    assert len(list(cap_dir.glob("capsule-*"))) == 1
    # the SLO's own verdict ring is part of the capsule history
    assert any(k.startswith("slo.samples") for k in cap["history"])


def test_capsule_rate_limit_retention_and_disabled_noop(tmp_path):
    # disabled: trigger is a no-op that never raises
    assert capsule.trigger("nonfinite", x=1) is None
    w = capsule.CapsuleWriter(str(tmp_path), keep=3, min_interval_s=1e9)
    assert w.trigger("weave_leak", detail="t0") is not None
    assert w.trigger("weave_leak", detail="t1") is None  # rate-limited
    assert metrics.Accumulator.get("capsule.rate_limited").value() == 1.0
    # retention: distinct reasons bypass the per-reason limit; keep=3 prunes
    for i in range(5):
        assert w.trigger(f"reason_{i}") is not None
    caps = sorted(p.name for p in tmp_path.glob("capsule-*"))
    assert len(caps) == 3


def test_weave_leak_aborts_with_capsule(tmp_path):
    capsule.configure(str(tmp_path / "caps"))
    from tools.oeweave.explore import SweepPolicy
    from tools.oeweave.scheduler import WeaveLeak, WeaveScheduler

    def leaky():
        ev = threading.Event()
        t = threading.Thread(target=ev.wait)
        t.start()
        # no stop path, no join: the planted lifecycle bug

    with pytest.raises(WeaveLeak):
        WeaveScheduler(SweepPolicy()).run(leaky)
    caps = list((tmp_path / "caps").glob("capsule-*-weave_leak.json.gz"))
    assert len(caps) == 1
    cap = capsule_report.load(str(caps[0]))
    assert "leaked" in cap["attrs"]["detail"]


# -- device-memory accounting -------------------------------------------------


def test_memory_model_analytic_matches_measured_exactly():
    """The acceptance pin: on the 8-device CPU mesh the analytic byte model
    agrees EXACTLY with the measured per-device shard bytes for every
    component both views price — base tables (array + hash), optimizer
    slots, hash keys, dense params — including after a hot-cache attach."""
    trainer, state, batch = _mesh_trainer(hot_rows=4)
    model = trainer.memory_model(state)
    analytic, measured = model["analytic"], model["measured"]
    # init attaches the (empty) hot caches, so both views price them
    overlap = set(analytic) & set(measured)
    assert {"table_weights/a", "table_slots/a", "table_weights/b",
            "table_slots/b", "table_keys/b", "hot/a", "hot/b",
            "dense_params"} <= overlap
    for key in sorted(overlap):
        assert analytic[key] == measured[key], (
            f"{key}: analytic {analytic[key]} != measured {measured[key]}")

    # still exact after a refresh installs real hot ids (content swap only)
    state = trainer.refresh_hot_rows(
        state, hot_ids={"a": np.arange(4, dtype=np.int64),
                        "b": np.asarray([(1 << 40) - 3], np.int64)})
    model = trainer.memory_model(state)
    analytic, measured = model["analytic"], model["measured"]
    for key in sorted(set(analytic) & set(measured)):
        assert analytic[key] == measured[key], (
            f"{key}: analytic {analytic[key]} != measured {measured[key]}")
    assert measured["hot/a"] == trainer._hot_device_bytes(
        trainer.model.ps_specs()["a"], 4)

    # publish: the ledger's gauges carry the same bytes
    trainer.publish_memory(state)
    total = metrics.Accumulator.get("memory.total_bytes", "gauge").value()
    assert total == model["device_total_bytes"]
    assert metrics.Accumulator.get(
        "memory.bytes", "gauge",
        labels={"component": "table_weights", "table": "a"}).value() \
        == measured["table_weights/a"]


def test_memory_model_zero_sharded_dense_slots_exact():
    trainer, state, batch = _mesh_trainer(dense_shard=True)
    model = trainer.memory_model(state)
    analytic, measured = model["analytic"], model["measured"]
    assert "zero_slots" in analytic and "zero_slots" in measured
    for key in sorted(set(analytic) & set(measured)):
        assert analytic[key] == measured[key], (
            f"{key}: analytic {analytic[key]} != measured {measured[key]}")


def test_preflight_rejects_over_budget_hot_attach():
    # hot_rows enabled AFTER init: the state carries no caches, so the next
    # refresh is the allocating "fill" — the one resize preflight gates
    trainer, state, batch = _mesh_trainer()
    trainer.hot_rows = 4
    assert state.tables["a"].hot is None
    hot_ids = {"a": np.arange(4, dtype=np.int64),
               "b": np.asarray([(1 << 40) - 3], np.int64)}
    memwatch.WATCH.configure(budget_bytes=64)  # nothing fits
    state2 = trainer.refresh_hot_rows(state, hot_ids=hot_ids)
    assert state2 is state  # rejected: the cache-free state is kept
    assert metrics.Accumulator.get("memory.preflight_rejects").value() == 1.0
    evs = [e for e in trace.RECORDER.tail()
           if getattr(e, "group", None) == "memory"
           and e.name == "preflight_reject"]
    assert evs and evs[-1].attrs["reason"] == "hot_fill"
    # with room, the same attach goes through
    memwatch.WATCH.configure(budget_bytes=None)
    state3 = trainer.refresh_hot_rows(state, hot_ids=hot_ids)
    assert state3.tables["a"].hot is not None


def test_placement_prime_preflight_keeps_current_sizes():
    from openembedding_tpu.placement import (PlacementController,
                                             PlacementPolicy)
    from openembedding_tpu.placement.policy import row_bytes
    from openembedding_tpu.utils.sketch import SkewMonitor
    trainer, state, batch = _mesh_trainer()
    mon = SkewMonitor(k=32, sync=True)
    for _ in range(3):  # warm the sketches so prime() sizes H > 0
        mon.observe("a", batch["sparse"]["a"])
        mon.observe("b", batch["sparse"]["b"])
    policy = PlacementPolicy(8 * row_bytes(8, 1), mig_rows=16)
    ctl = PlacementController(trainer, policy, monitor=mon)
    memwatch.WATCH.configure(budget_bytes=8)  # the resize delta cannot fit
    state2 = ctl.prime(state)
    assert not trainer.hot_rows  # sizes kept at their current values
    evs = [e for e in trace.RECORDER.tail()
           if getattr(e, "group", None) == "placement"
           and e.name == "prime_rejected"]
    assert evs, "prime under budget pressure must leave a flight event"
    assert state2.tables["a"].hot is None


# -- reporter JSONL rotation --------------------------------------------------


def test_jsonl_rotation_boundary_never_splits_a_record(tmp_path):
    path = tmp_path / "metrics.jsonl"
    metrics.observe("train.steps", 1.0)
    rep = metrics.PeriodicReporter(interval=60, sink=lambda s: None,
                                   jsonl_path=str(path), jsonl_max_bytes=150,
                                   jsonl_keep=2, history=False)
    for _ in range(6):
        rep._tick()
    files = [path] + [tmp_path / f"metrics.jsonl.{i}" for i in (1, 2)]
    assert all(f.exists() for f in files)
    assert not (tmp_path / "metrics.jsonl.3").exists()  # keep=2 bound
    for f in files:
        body = f.read_text()
        assert len(body.encode()) <= 150  # every file under the bound
        for line in body.splitlines():   # and every record intact
            rec = json.loads(line)
            assert "ts" in rec and "metrics" in rec


def test_jsonl_unbounded_when_rotation_off(tmp_path):
    path = tmp_path / "m.jsonl"
    metrics.observe("train.steps", 1.0)
    rep = metrics.PeriodicReporter(interval=60, sink=lambda s: None,
                                   jsonl_path=str(path), history=False)
    for _ in range(4):
        rep._tick()
    assert len(path.read_text().splitlines()) == 4
    assert not (tmp_path / "m.jsonl.1").exists()
