"""Multivalent (ragged) feature pooling: the `combiner` surface.

The reference's `Variable.sparse_read` accepts RaggedTensors
(`tensorflow/exb.py:308-327`) and its consumers pool the ragged rows
(TF `safe_embedding_lookup_sparse` combiners). The TPU-native answer keeps
static shapes: `data.pad_ragged` pads variable-length id lists to a fixed
field width with -1, and `EmbeddingSpec.combiner` ("sum"/"mean"/"sqrtn")
pools the field axis with the pad slots masked out of both the value and the
gradient (`embedding.combine`). These tests pin that equivalence end to end:
value vs numpy varlen pooling, gradient parity, mesh-exchange parity, the
sparse_as_dense path, serving/export, and the ragged host-side helpers."""

import dataclasses
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.data import is_ragged, pad_ragged
from openembedding_tpu.embedding import EmbeddingSpec, combine, valid_mask

VOCAB, DIM, B, F = 64, 4, 16, 5


class PooledDense(nn.Module):
    """Dense tower over POOLED rows (B, dim) — the module a combiner model
    feeds."""

    @nn.compact
    def __call__(self, embedded, dense_inputs):
        parts = [embedded[k].reshape(embedded[k].shape[0], -1)
                 for k in sorted(embedded)]
        if dense_inputs is not None:
            parts.append(dense_inputs)
        return nn.Dense(1)(jnp.concatenate(parts, axis=-1))[:, 0]


class SumInModule(nn.Module):
    """The no-combiner control: pools (B, F, dim) -> (B, dim) by UNMASKED sum
    inside the module. Because pad slots pull zero rows and -1 grads train no
    row (pinned in test_embedding.py), this trains identically to
    combiner='sum' — the parity that proves the combiner's gradient path."""

    @nn.compact
    def __call__(self, embedded, dense_inputs):
        parts = [embedded[k].sum(axis=-2) for k in sorted(embedded)]
        if dense_inputs is not None:
            parts.append(dense_inputs)
        return nn.Dense(1)(jnp.concatenate(parts, axis=-1))[:, 0]


def ragged_batch(rng, batch=B, width=F, vocab=VOCAB):
    """Variable-length rows (1..width ids) padded to width with -1."""
    lens = rng.integers(1, width + 1, size=(batch,))
    ids = np.full((batch, width), -1, np.int64)
    for r, ln in enumerate(lens):
        ids[r, :ln] = rng.integers(0, vocab, size=(ln,))
    label = (lens % 2).astype(np.float32)
    return {"sparse": {"emb": jnp.asarray(ids)}, "dense": None,
            "label": jnp.asarray(label)}, lens


def ragged_hash_batch(seed, id_space=1 << 62):
    """Ragged 63-bit hash-table batch in the x64-appropriate layout (split
    pairs when x64 is off, plain int64 when on — production feed convention).
    -> (batch, lens)."""
    from openembedding_tpu.ops.id64 import np_split_ids
    r = np.random.default_rng(seed)
    lens = r.integers(1, F + 1, size=(B,))
    ids64 = np.full((B, F), -1, np.int64)
    for row, ln in enumerate(lens):
        ids64[row, :ln] = r.integers(0, id_space, size=(ln,))
    feed = (jnp.asarray(ids64) if jax.config.jax_enable_x64
            else jnp.asarray(np_split_ids(ids64)))
    return {"sparse": {"emb": feed}, "dense": None,
            "label": jnp.asarray((lens % 2).astype(np.float32))}, lens


def np_pool(table, ids, combiner):
    """Numpy oracle: true variable-length pooling over the valid prefix."""
    out = np.zeros((ids.shape[0], table.shape[1]), np.float32)
    for r in range(ids.shape[0]):
        sel = ids[r][ids[r] >= 0]
        if len(sel) == 0:
            continue
        rows = table[sel]
        if combiner == "sum":
            out[r] = rows.sum(0)
        elif combiner == "mean":
            out[r] = rows.mean(0)
        else:
            out[r] = rows.sum(0) / np.sqrt(len(sel))
    return out


# ---------------------------------------------------------------- unit level

def test_pad_ragged_and_is_ragged():
    seqs = [[1, 2, 3], [7], [4, 5]]
    assert is_ragged(seqs)
    padded = pad_ragged(seqs)
    np.testing.assert_array_equal(
        padded, [[1, 2, 3], [7, -1, -1], [4, 5, -1]])
    assert pad_ragged(seqs, width=4).shape == (3, 4)
    with pytest.raises(ValueError):
        pad_ragged(seqs, width=2)  # silent truncation refused
    assert not is_ragged([[1, 2], [3, 4]])          # rectangular
    assert not is_ragged(np.zeros((3, 2), np.int64))
    assert pad_ragged([], width=3).shape == (0, 3)
    assert pad_ragged([[]]).shape == (1, 1)          # all-empty row -> all-pad


@pytest.mark.parametrize("combiner", ["sum", "mean", "sqrtn"])
def test_combine_matches_numpy_varlen(combiner):
    rng = np.random.default_rng(0)
    table = rng.standard_normal((VOCAB, DIM)).astype(np.float32)
    ids = np.full((6, 4), -1, np.int64)
    for r, ln in enumerate([1, 2, 3, 4, 2, 0]):     # incl. an ALL-PAD row
        ids[r, :ln] = rng.integers(0, VOCAB, size=(ln,))
    spec = EmbeddingSpec(name="e", input_dim=VOCAB, output_dim=DIM,
                         combiner=combiner)
    rows = jnp.where(jnp.asarray(ids)[..., None] >= 0,
                     jnp.asarray(table)[jnp.clip(jnp.asarray(ids), 0)], 0.0)
    got = np.asarray(combine(spec, jnp.asarray(ids), rows))
    np.testing.assert_allclose(got, np_pool(table, ids, combiner),
                               rtol=1e-6, atol=1e-6)
    # all-pad row pools to zeros, not NaN (mean/sqrtn clamp the count)
    assert np.all(np.isfinite(got)) and np.all(got[5] == 0.0)


def test_combine_gradient_masks_pad_slots():
    """d(pooled)/d(row) is mask/count — pad slots get EXACTLY zero grad, so a
    pad slot can never train whatever row its -1 scatter might alias."""
    spec = EmbeddingSpec(name="e", input_dim=VOCAB, output_dim=DIM,
                         combiner="mean")
    ids = jnp.asarray([[3, 9, -1, -1]])
    rows = jnp.asarray(np.random.default_rng(1).standard_normal(
        (1, 4, DIM)).astype(np.float32))
    g = jax.grad(lambda r: combine(spec, ids, r).sum())(rows)
    np.testing.assert_allclose(np.asarray(g[0, :2]), 0.5, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(g[0, 2:]), 0.0)


def test_combiner_validation():
    with pytest.raises(ValueError, match="combiner"):
        EmbeddingSpec(name="e", input_dim=8, output_dim=2, combiner="max")
    spec = EmbeddingSpec(name="e", input_dim=8, output_dim=2, combiner="sum")
    again = EmbeddingSpec.from_config(spec.to_config())
    assert again.combiner == "sum" and again == spec
    # pre-combiner configs (older checkpoints) default to no pooling
    cfg = spec.to_config()
    del cfg["combiner"]
    assert EmbeddingSpec.from_config(cfg).combiner == ""
    with pytest.raises(ValueError, match="rank"):
        combine(spec, jnp.asarray([1, 2]), jnp.zeros((2, 2)))


def test_valid_mask_pair_layout():
    from openembedding_tpu.ops.id64 import np_split_ids
    spec = EmbeddingSpec(name="e", input_dim=-1, output_dim=DIM, capacity=64,
                         combiner="mean")
    ids64 = np.asarray([[5, -1], [(1 << 40) + 3, 7]], np.int64)
    m = np.asarray(valid_mask(spec, jnp.asarray(np_split_ids(ids64))))
    np.testing.assert_array_equal(m, ids64 >= 0)


# ------------------------------------------------------------- training path

def test_combiner_sum_trains_identically_to_in_module_pooling():
    """combiner='sum' + PooledDense vs no combiner + SumInModule: same specs
    (same variable_id/seed -> same table init), same dense init, and — because
    pad rows are zero and -1 grads train nothing — the SAME training
    trajectory. This is the gradient-path parity proof."""
    rng = np.random.default_rng(7)
    opt = embed.Adagrad(learning_rate=0.1)

    def build(module, combiner):
        layer = embed.Embedding(VOCAB, DIM, name="emb", combiner=combiner)
        model = embed.EmbeddingModel(module, [layer])
        return embed.Trainer(model, optimizer=opt)

    ta = build(PooledDense(), "sum")
    tb = build(SumInModule(), "")
    batch, _ = ragged_batch(rng)
    sa, sb = ta.init(batch), tb.init(batch)
    np.testing.assert_array_equal(np.asarray(sa.tables["emb"].weights),
                                  np.asarray(sb.tables["emb"].weights))
    stepa, stepb = ta.jit_train_step(), tb.jit_train_step()
    for i in range(3):
        b, _ = ragged_batch(rng)
        sa, ma = stepa(sa, b)
        sb, mb = stepb(sb, b)
        np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                                   rtol=1e-6, err_msg=f"step {i}")
    np.testing.assert_allclose(np.asarray(sa.tables["emb"].weights),
                               np.asarray(sb.tables["emb"].weights),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("combiner", ["mean", "sqrtn"])
def test_combiner_eval_matches_manual_math(combiner):
    """eval logits == numpy varlen pooling pushed through the Dense(1) params
    by hand — the full value path with no jax on the oracle side."""
    rng = np.random.default_rng(3)
    layer = embed.Embedding(VOCAB, DIM, name="emb", combiner=combiner)
    model = embed.EmbeddingModel(PooledDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.1))
    batch, _ = ragged_batch(rng)
    state = trainer.init(batch)
    got = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
    table = np.asarray(state.tables["emb"].weights)
    pooled = np_pool(table, np.asarray(batch["sparse"]["emb"]), combiner)
    dense = state.dense_params["Dense_0"]
    want = pooled @ np.asarray(dense["kernel"]) + np.asarray(dense["bias"])
    np.testing.assert_allclose(got, want[:, 0], rtol=1e-5, atol=1e-6)


def test_combiner_mesh_matches_single_device():
    """The sharded exchange (pad ids ride the sentinel-filled buckets) pools
    identically to the single-device oracle. Same pattern as
    test_mesh.test_mesh_trainer_matches_single_device: Constant table init
    (sharding-independent), the oracle scales its loss by S to match the
    mesh's summed local-mean gradients, step-0 row updates must agree."""
    from openembedding_tpu.parallel import (MeshTrainer, deinterleave_rows,
                                            make_mesh)

    S = 8  # conftest's virtual CPU mesh
    rng = np.random.default_rng(11)
    batch, _ = ragged_batch(rng, batch=8 * S)

    def build(cls, loss_scale=1.0, **kw):
        layer = embed.Embedding(VOCAB, DIM, name="emb", combiner="mean",
                                embeddings_initializer=embed.Constant(0.1))
        model = embed.EmbeddingModel(
            PooledDense(), [layer],
            loss_fn=lambda lo, la: loss_scale * embed.model.binary_logloss(
                lo, la))
        return cls(model, optimizer=embed.Adagrad(learning_rate=0.1), **kw)

    single = build(embed.Trainer, loss_scale=float(S))
    ss = single.init(batch)
    ss, _ = jax.jit(single.train_step)(ss, batch)

    meshed = build(MeshTrainer, mesh=make_mesh())
    sm = meshed.init(batch)
    sm, _ = meshed.jit_train_step(batch, sm)(sm, batch)

    w_mesh = np.asarray(deinterleave_rows(sm.tables["emb"].weights, S, VOCAB))
    w_single = np.asarray(ss.tables["emb"].weights)
    np.testing.assert_allclose(w_mesh, w_single, rtol=1e-5, atol=1e-6)
    # pad slots trained nothing on either side: rows no batch id touches
    untouched = np.setdiff1d(np.arange(VOCAB),
                             np.asarray(batch["sparse"]["emb"]))
    np.testing.assert_allclose(w_single[untouched], np.float32(0.1),
                               rtol=0, atol=0)


def test_combiner_sparse_as_dense():
    """sad tables (dense-mirrored 'Cache' mode) pool through the same combine:
    pad slots (-1 take-clamps to row 0) are masked out of value AND grad, so
    row 0 never trains from a pad slot."""
    rng = np.random.default_rng(5)
    layer = embed.Embedding(VOCAB, DIM, name="emb", sparse_as_dense=True,
                            combiner="mean")
    model = embed.EmbeddingModel(PooledDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.5))
    # no row-0 ids anywhere: if a pad slot leaked grad, row 0 would move
    ids = np.asarray([[1, 2, -1, -1, -1], [3, -1, -1, -1, -1]], np.int64)
    batch = {"sparse": {"emb": jnp.asarray(ids)}, "dense": None,
             "label": jnp.asarray([1.0, 0.0])}
    state = trainer.init(batch)
    t0 = np.asarray(state.dense_params["__embeddings__"]["emb"])
    ev = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
    pooled = np_pool(t0, ids, "mean")
    dense = state.dense_params["Dense_0"]
    want = pooled @ np.asarray(dense["kernel"]) + np.asarray(dense["bias"])
    np.testing.assert_allclose(ev, want[:, 0], rtol=1e-5, atol=1e-6)
    step = trainer.jit_train_step()
    state, _ = step(state, batch)
    t1 = np.asarray(state.dense_params["__embeddings__"]["emb"])
    np.testing.assert_array_equal(t1[0], t0[0])          # row 0 untouched
    assert not np.allclose(t1[[1, 2, 3]], t0[[1, 2, 3]])  # real rows train


def test_combiner_hash_table_63bit_ids():
    """63-bit hash-table ids with ragged padding (-1 / EMPTY pair): pooled
    lookup matches the numpy oracle on the valid prefix. The id layout follows
    the x64 config exactly like production feeds do: split pairs when x64 is
    off (`ops/id64.py`), plain int64 when on (pair tables don't exist there)."""
    layer = embed.Embedding(-1, DIM, name="emb", capacity=256,
                            combiner="sum")
    model = embed.EmbeddingModel(PooledDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.1))
    batch, lens = ragged_hash_batch(9)
    state = trainer.init(batch)
    step = trainer.jit_train_step()
    s1, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
    # pooled rows via the model == sum over the valid prefix of the raw pull
    raw = np.asarray(trainer.table_lookup(
        model.specs["emb"], s1.tables["emb"], batch["sparse"]["emb"]))
    got = np.asarray(trainer.jit_eval_step()(s1, batch)["logits"])
    dense = s1.dense_params["Dense_0"]
    want = (np.stack([raw[r, :lens[r]].sum(0) for r in range(B)])
            @ np.asarray(dense["kernel"]) + np.asarray(dense["bias"]))
    np.testing.assert_allclose(got, want[:, 0], rtol=1e-5, atol=1e-6)


def test_variable_sparse_read_accepts_ragged():
    """The imperative facade takes the reference's ragged input directly:
    list-of-lists pad to the batch max with -1; pad slots pull zero rows."""
    spec = EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM)
    var = embed.EmbeddingVariable(spec, embed.SGD(learning_rate=0.1))
    rows = np.asarray(var.sparse_read([[1, 2, 3], [5], [7, 8]]))
    assert rows.shape == (3, 3, DIM)
    dense_rows = np.asarray(var.read_only_pull([[1, 2, 3], [5], [7, 8]]))
    np.testing.assert_array_equal(rows, dense_rows)
    assert np.all(rows[1, 1:] == 0.0) and np.all(rows[2, 2:] == 0.0)
    np.testing.assert_array_equal(rows[0, :3],
                                  np.asarray(var.read_only_pull([1, 2, 3])))


def test_np_valid_mask_both_layouts():
    from openembedding_tpu.embedding import np_valid_mask
    from openembedding_tpu.ops.id64 import np_split_ids
    spec = EmbeddingSpec(name="e", input_dim=-1, output_dim=DIM, capacity=64)
    big = (1 << 40) + (1 << 31) + 5  # bit 31 set: int32 truncation goes negative
    ids64 = np.asarray([[big, -1], [7, 3]], np.int64)
    np.testing.assert_array_equal(np_valid_mask(spec, ids64), ids64 >= 0)
    np.testing.assert_array_equal(
        np_valid_mask(spec, np_split_ids(ids64)), ids64 >= 0)


def test_sad_pads_pull_zero_and_train_nothing():
    """sparse_as_dense WITHOUT a combiner: -1 pads must honor the same
    contract as every other lookup path — zero rows, zero grads. A bare
    jnp.take would wrap -1 onto the LAST table row in value and gradient
    (model.sad_rows is the fix)."""
    layer = embed.Embedding(VOCAB, DIM, name="emb", sparse_as_dense=True)
    model = embed.EmbeddingModel(SumInModule(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.5))
    # neither row 0 nor the last row appears; only pads could touch them
    ids = np.asarray([[1, 2, -1], [3, -1, -1]], np.int64)
    batch = {"sparse": {"emb": jnp.asarray(ids)}, "dense": None,
             "label": jnp.asarray([1.0, 0.0])}
    state = trainer.init(batch)
    t0 = np.asarray(state.dense_params["__embeddings__"]["emb"])
    got = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
    dense = state.dense_params["Dense_0"]
    want = (np_pool(t0, ids, "sum") @ np.asarray(dense["kernel"])
            + np.asarray(dense["bias"]))
    np.testing.assert_allclose(got, want[:, 0], rtol=1e-5, atol=1e-6)
    state, _ = trainer.jit_train_step()(state, batch)
    t1 = np.asarray(state.dense_params["__embeddings__"]["emb"])
    np.testing.assert_array_equal(t1[-1], t0[-1])  # -1 pad wrapped nowhere
    np.testing.assert_array_equal(t1[0], t0[0])
    assert not np.allclose(t1[[1, 2, 3]], t0[[1, 2, 3]])


def test_serving_mask_survives_x64_off(tmp_path):
    """Regression: StandaloneModel.predict's combiner mask must come from the
    host int64 ids. Under x64-off (the production default — this suite forces
    x64 ON, so this runs a child interpreter) `jnp.asarray` truncates a 63-bit
    id with bit 31 set to a NEGATIVE int32; a device-derived mask would mark
    it padding and silently drop its row from the pooled sum."""
    import subprocess
    import sys
    import textwrap

    child = textwrap.dedent("""
        import numpy as np, jax, jax.numpy as jnp
        assert not jax.config.jax_enable_x64
        import flax.linen as nn
        import openembedding_tpu as embed
        from openembedding_tpu.export import StandaloneModel, export_standalone
        from openembedding_tpu.ops.id64 import np_split_ids

        class Tower(nn.Module):
            @nn.compact
            def __call__(self, embedded, dense_inputs):
                return nn.Dense(1)(embedded["emb"])[:, 0]

        BIG = (1 << 40) + (1 << 31) + 5
        layer = embed.Embedding(-1, 4, name="emb", capacity=64,
                                combiner="sum")
        model = embed.EmbeddingModel(Tower(), [layer])
        trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.1))
        ids64 = np.asarray([[BIG, 7]], np.int64)
        batch = {"sparse": {"emb": jnp.asarray(np_split_ids(ids64))},
                 "dense": None, "label": jnp.asarray([1.0])}
        state = trainer.init(batch)
        state, _ = trainer.jit_train_step()(state, batch)
        export_standalone(state, model, r"%(path)s")
        served = StandaloneModel.load(r"%(path)s", model=model)

        def p(ids):
            return np.asarray(served.predict(
                {"sparse": {"emb": np.asarray(ids, np.int64)}}))

        full = p([[BIG, 7]])
        # sum pooling: an explicit pad changes nothing; dropping BIG must
        with np.errstate(all="ignore"):
            assert np.allclose(full, p([[BIG, 7, -1]]), atol=1e-6), "pad leaked"
            assert not np.allclose(full, p([[7, -1]]), atol=1e-4), \\
                "BIG id's row was dropped from the pool (mask truncation)"

        # EmbeddingVariable ragged coercion must split 63-bit ids host-side:
        # truncation would alias BIG and BIG+2^32 onto one row
        spec = embed.embedding.EmbeddingSpec(name="v", input_dim=-1,
                                             output_dim=4, capacity=64)
        var = embed.EmbeddingVariable(spec, embed.SGD(learning_rate=0.1))
        rows = np.asarray(var.sparse_read([[BIG, BIG + (1 << 32)], [7]]))
        assert rows.shape == (2, 2, 4) and (rows[1, 1:] == 0).all()
        assert not np.allclose(rows[0, 0], rows[0, 1]), \\
            "63-bit ragged ids collided mod 2^32 (int64 truncation)"
        again = np.asarray(var.read_only_pull([[BIG]]))
        assert np.allclose(again[0, 0], rows[0, 0])
        print("CHILD OK")
    """) % {"path": str(tmp_path / "m")}
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_ENABLE_X64", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0 and "CHILD OK" in r.stdout, (
        r.stdout[-2000:], r.stderr[-2000:])


def test_variable_ragged_pull_push_roundtrip():
    """The reference pull/push contract with ragged input end to end:
    sparse_read(ragged) -> grads shaped like the padded rows ->
    push_gradients(SAME ragged ids) -> update_weights. Pad slots' grads go
    nowhere; real rows take exactly their own update."""
    spec = EmbeddingSpec(name="v", input_dim=VOCAB, output_dim=DIM)
    var = embed.EmbeddingVariable(spec, embed.SGD(learning_rate=1.0))
    seqs = [[1, 2, 3], [5]]
    rows = var.sparse_read(seqs)
    w0 = np.asarray(var.state.weights).copy()
    grads = np.ones(np.asarray(rows).shape, np.float32)
    var.push_gradients(seqs, grads)
    var.update_weights()
    w1 = np.asarray(var.state.weights)
    for r in (1, 2, 3, 5):
        np.testing.assert_allclose(w1[r], w0[r] - 1.0, rtol=1e-6)
    touched = np.zeros(VOCAB, bool)
    touched[[1, 2, 3, 5]] = True
    np.testing.assert_array_equal(w1[~touched], w0[~touched])


def test_combiner_export_serving_roundtrip(tmp_path):
    """export_standalone -> StandaloneModel.predict pools multivalent features
    exactly like the trainer's eval step (incl. request-bucket batch padding)."""
    from openembedding_tpu.export import StandaloneModel, export_standalone

    rng = np.random.default_rng(13)
    layer = embed.Embedding(VOCAB, DIM, name="emb", combiner="mean")
    model = embed.EmbeddingModel(PooledDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.1))
    batch, _ = ragged_batch(rng, batch=6)  # 6 -> pads to the 8-bucket
    state = trainer.init(batch)
    state, _ = trainer.jit_train_step()(state, batch)
    want = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
    path = str(tmp_path / "standalone")
    export_standalone(state, model, path)
    served = StandaloneModel.load(path, model=model)
    got = np.asarray(served.predict(
        {"sparse": {k: np.asarray(v) for k, v in batch["sparse"].items()}}))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_combiner_composes_with_host_offload():
    """Multivalent pooling over a host-cached (>HBM) hash table: ragged
    batches drive offload_train_many (union admission + fused scan), and the
    eval pooling matches the valid-prefix numpy oracle — a cache path that
    admitted or pooled pad slots would break the equality, not just
    finiteness."""
    layer = embed.Embedding(-1, DIM, name="emb", capacity=512,
                            storage="host_cached", combiner="mean")
    model = embed.EmbeddingModel(PooledDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.Adagrad(learning_rate=0.1))

    pairs = [ragged_hash_batch(s, id_space=1 << 40) for s in (1, 2)]
    batches, lens0 = [p[0] for p in pairs], pairs[0][1]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)
    state = trainer.init(batches[0])
    state, m = trainer.offload_train_many(state, stacked)
    assert np.isfinite(np.asarray(m["loss"])).all()
    assert trainer.offload["emb"].resident_count > 0
    # pooled eval == mean over the valid prefix of the raw cached-table pull
    feed = batches[0]["sparse"]["emb"]
    raw = np.asarray(trainer.table_lookup(
        model.specs["emb"], state.tables["emb"], feed))
    got = np.asarray(trainer.jit_eval_step()(state, batches[0])["logits"])
    dense = state.dense_params["Dense_0"]
    pooled = np.stack([raw[r, :lens0[r]].mean(0) for r in range(B)])
    want = pooled @ np.asarray(dense["kernel"]) + np.asarray(dense["bias"])
    np.testing.assert_allclose(got, want[:, 0], rtol=1e-5, atol=1e-6)


def test_randomized_combiner_parity_sweep():
    """Randomized breadth for the pooling path: {combiner} x {array, hash} x
    random (batch, width, vocab, lengths incl. all-pad rows) — every config's
    eval must match the numpy varlen oracle computed from the raw pull. A
    masking/validity bug anywhere in the lookup->combine->dense chain shows
    up as a value mismatch, not a shape error."""
    rng = np.random.default_rng(2024)
    for trial in range(12):
        combiner = ["sum", "mean", "sqrtn"][trial % 3]
        hashed = bool(trial % 2)
        batch_n = int(rng.integers(2, 12))
        width = int(rng.integers(1, 7))
        vocab = int(rng.integers(16, 200))
        layer = (embed.Embedding(-1, DIM, name="emb", capacity=512,
                                 combiner=combiner) if hashed
                 else embed.Embedding(vocab, DIM, name="emb",
                                      combiner=combiner))
        model = embed.EmbeddingModel(PooledDense(), [layer])
        trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.1),
                                seed=trial)
        ids = np.full((batch_n, width), -1, np.int64)
        lens = rng.integers(0, width + 1, size=(batch_n,))  # 0 = all-pad row
        if (lens == 0).all():
            lens[0] = 1  # at least one real id in the batch
        for r, ln in enumerate(lens):
            ids[r, :ln] = rng.integers(0, vocab, size=(ln,))
        batch = {"sparse": {"emb": jnp.asarray(ids)}, "dense": None,
                 "label": jnp.asarray((lens % 2).astype(np.float32))}
        state = trainer.init(batch)
        state, m = trainer.jit_train_step()(state, batch)
        assert np.isfinite(float(m["loss"])), (trial, combiner, hashed)
        raw = np.asarray(trainer.table_lookup(
            model.specs["emb"], state.tables["emb"], jnp.asarray(ids)))
        got = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
        pooled = np_pool_rows(raw, lens, combiner)
        dense = state.dense_params["Dense_0"]
        want = pooled @ np.asarray(dense["kernel"]) + np.asarray(dense["bias"])
        np.testing.assert_allclose(
            got, want[:, 0], rtol=1e-5, atol=1e-6,
            err_msg=f"trial {trial}: {combiner} hashed={hashed} "
                    f"B={batch_n} W={width} V={vocab}")


def np_pool_rows(raw, lens, combiner):
    """Varlen-pool pre-pulled rows (B, W, d) over each row's valid prefix."""
    out = np.zeros((raw.shape[0], raw.shape[-1]), np.float32)
    for r, ln in enumerate(lens):
        if ln == 0:
            continue
        rows = raw[r, :ln]
        out[r] = (rows.sum(0) if combiner == "sum"
                  else rows.mean(0) if combiner == "mean"
                  else rows.sum(0) / np.sqrt(ln))
    return out
