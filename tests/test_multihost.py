"""Multi-host glue, single-process fast checks (divisibility, reader sharding).
The REAL multi-process paths — make_array_from_process_local_data, per-process
shard writes, the persist commit protocol — are exercised with spawned
jax.distributed processes in `tests/test_multiprocess.py`."""

import numpy as np
import pytest

import jax

from openembedding_tpu.parallel import make_mesh, multihost


def test_initialize_noop_single_process():
    multihost.initialize()  # must not raise on a single process
    assert multihost.num_hosts() == 1
    assert multihost.host_id() == 0


def test_global_batch_shards_over_mesh():
    mesh = make_mesh()
    batch = {"sparse": {"categorical": np.arange(16 * 4).reshape(16, 4)},
             "label": np.ones((16,), np.float32)}
    out = multihost.global_batch(batch, mesh)
    assert out["sparse"]["categorical"].shape == (16, 4)
    shard_shapes = {s.data.shape for s in out["sparse"]["categorical"]
                    .addressable_shards}
    assert shard_shapes == {(2, 4)}  # 16 rows over 8 devices
    np.testing.assert_array_equal(np.asarray(out["sparse"]["categorical"]),
                                  batch["sparse"]["categorical"])


def test_host_sharded_reader_batches(tmp_path):
    rng = np.random.default_rng(0)
    path = str(tmp_path / "t.tsv")
    with open(path, "w") as f:
        for _ in range(64):
            cols = ["1"] + [str(int(x)) for x in rng.integers(0, 9, 13)] + \
                   [f"{int(x):x}" for x in rng.integers(0, 1 << 20, 26)]
            f.write("\t".join(cols) + "\n")
    mesh = make_mesh()
    it = multihost.host_sharded_reader([path], 16, mesh, id_space=1 << 20)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0]["sparse"]["categorical"].shape == (16, 26)


def test_host_sharded_reader_divisibility(tmp_path, monkeypatch):
    monkeypatch.setattr(multihost, "num_hosts", lambda: 3)
    mesh = make_mesh()
    with pytest.raises(ValueError, match="divisible"):
        next(iter(multihost.host_sharded_reader(["x.tsv"], 16, mesh)))
