"""Scan-fused multi-step training (jit_train_many) must equal step-by-step."""

import numpy as np

import jax

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.parallel import MeshTrainer, make_mesh

VOCAB = 1 << 10
K = 4


def _stack(batches):
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)


def test_train_many_matches_step_by_step():
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=K, seed=3))

    tr = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=1)
    state_a = tr.init(batches[0])
    step = tr.jit_train_step()
    losses_a = []
    for b in batches:
        state_a, m = step(state_a, b)
        losses_a.append(float(m["loss"]))

    state_b = tr.init(batches[0])
    state_b, metrics = tr.jit_train_many()(state_b, _stack(batches))
    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses_a,
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(state_a.tables["categorical"].weights),
        np.asarray(state_b.tables["categorical"].weights))
    assert int(state_b.step) == K


def test_mesh_train_many_matches_step_by_step():
    mesh = make_mesh()
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=K, seed=5))

    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=1)
    state_a = tr.init(batches[0])
    step = tr.jit_train_step(batches[0], state_a)
    losses_a = []
    for b in batches:
        state_a, m = step(state_a, b)
        losses_a.append(float(m["loss"]))

    tr2 = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), seed=1)
    state_b = tr2.init(batches[0])
    stacked = _stack(batches)
    state_b, metrics = tr2.jit_train_many(stacked, state_b)(state_b, stacked)
    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses_a,
                               rtol=1e-6, atol=1e-6)
    # scan body and standalone step may fuse differently (observed 7.5e-9
    # max abs on this container's CPU XLA) — near-ulp, not a protocol skew
    np.testing.assert_allclose(
        np.asarray(state_a.tables["categorical"].weights),
        np.asarray(state_b.tables["categorical"].weights),
        rtol=1e-5, atol=1e-7)
