"""Host-offload integrated into the trainers (storage="host_cached"):

- `Trainer`/`MeshTrainer` build the two-tier table from the spec alone and the
  `offload_prepare` driver admits each batch around the jitted step — training
  a table LARGER than the device cache must match in-HBM training on the same
  stream (the reference trains 175 GB models through a DRAM cache the same way,
  `variable/PmemEmbeddingOptimizerVariable.h:88-198`).
- checkpoints and persists round-trip through the host store, interoperating
  with non-offloaded trainers in both directions (the reference's PMem dump is
  loadable by DRAM servers and vice versa, `EmbeddingInitOperator.cpp:146-168`).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.embedding import EmbeddingSpec, lookup
from openembedding_tpu.initializers import Constant
from openembedding_tpu.model import EmbeddingModel, Trainer
from openembedding_tpu.models import make_lr
from openembedding_tpu.parallel import MeshTrainer, make_mesh

DIM = 4
CACHE = 64          # device cache slots — far smaller than the id space
BIG = 4096          # "infinite" in-HBM capacity for the oracle trainer
ID_SPACE = 1 << 40  # forces the hash path; ids never fit the cache


def _batches(steps=8, batch=16, seed=11):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        ids = rng.integers(0, ID_SPACE, size=(batch, 2)).astype(np.int64)
        labels = (rng.random(batch) < 0.5).astype(np.float32)
        out.append({"sparse": {"categorical": ids}, "label": labels})
    return out


def _model(capacity, storage):
    # Constant init => identical first-touch rows whatever slot an id lands in,
    # so cached and uncached runs are exactly comparable (the documented
    # init-on-slot divergence of tables/hash_table.py is sidestepped)
    e = embed.Embedding(-1, DIM, name="categorical", capacity=capacity,
                        storage=storage, embeddings_initializer=Constant(0.0))
    lr = make_lr(vocabulary=-1, hashed=True, capacity=capacity)
    return EmbeddingModel(lr.module, [e], loss_fn=lr.loss_fn, config=lr.config)


def _train(trainer, batches):
    state = trainer.init(batches[0])
    step = (trainer.jit_train_step(batches[0], state)
            if isinstance(trainer, MeshTrainer) else trainer.jit_train_step())
    losses = []
    for b in batches:
        state = trainer.offload_prepare(state, b)
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    return trainer, state, losses


def _rows(trainer, state, ids):
    """Final per-id rows, wherever they live."""
    if trainer.offload:
        ot = trainer.offload["categorical"]
        ot.adopt(state.tables["categorical"])  # post-step state (donation)
        return ot.lookup_anywhere(ids)
    spec = trainer.model.specs["categorical"]
    if isinstance(trainer, MeshTrainer):
        # read through the sharded read-only pull on a replicated id batch
        from openembedding_tpu.parallel.sharded import sharded_lookup
        import functools
        from jax.sharding import PartitionSpec as P
        pull = jax.jit(jax.shard_map(
            functools.partial(sharded_lookup, spec, axis=trainer.axis),
            mesh=trainer.mesh,
            in_specs=(trainer._table_pspec(spec), P()),
            out_specs=P(), check_vma=False))
        return np.asarray(pull(state.tables["categorical"], jnp.asarray(ids)))
    return np.asarray(lookup(spec, state.tables["categorical"],
                             jnp.asarray(ids)))


def test_trainer_offload_matches_in_hbm():
    """Same stream, one trainer with a 64-slot cache (flushes forced), one with
    a big in-HBM table: loss trajectory and final rows must match."""
    batches = _batches()
    oracle, ostate, olosses = _train(
        Trainer(_model(BIG, "hbm"), embed.Adagrad(learning_rate=0.3)), batches)
    cached, cstate, closses = _train(
        Trainer(_model(CACHE, "host_cached"),
                embed.Adagrad(learning_rate=0.3)), batches)
    assert cached.offload  # the spec knob really engaged the two-tier table
    assert cached.offload["categorical"].store.ids.size > 0  # flushes happened
    np.testing.assert_allclose(closses, olosses, rtol=1e-5, atol=1e-6)

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"] for b in batches]))
    np.testing.assert_allclose(_rows(cached, cstate, ids),
                               _rows(oracle, ostate, ids),
                               rtol=1e-5, atol=1e-6)


def test_mesh_offload_matches_in_hbm():
    """The row-sharded cache on an 8-device mesh: per-shard admission must feed
    the sharded pull/push protocol exactly like a big in-HBM sharded table."""
    mesh = make_mesh()
    batches = _batches(steps=6)
    oracle, ostate, olosses = _train(
        MeshTrainer(_model(BIG, "hbm"), embed.Adagrad(learning_rate=0.3),
                    mesh=mesh), batches)
    cached, cstate, closses = _train(
        MeshTrainer(_model(CACHE * 8, "host_cached"),
                    embed.Adagrad(learning_rate=0.3), mesh=mesh), batches)
    ot = cached.offload["categorical"]
    assert ot.num_shards == 8
    np.testing.assert_allclose(closses, olosses, rtol=1e-5, atol=1e-6)

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"] for b in batches]))
    np.testing.assert_allclose(_rows(cached, cstate, ids),
                               _rows(oracle, ostate, ids),
                               rtol=1e-5, atol=1e-6)


def test_mesh_offload_flushes_under_pressure():
    """A cache sized below the unique-id volume must flush (store grows) and
    keep training losslessly (vs the big-cache run of the same stream)."""
    mesh = make_mesh()
    batches = _batches(steps=10, batch=32, seed=5)
    small = MeshTrainer(_model(24 * 8, "host_cached"),
                        embed.Adagrad(learning_rate=0.3), mesh=mesh)
    small, sstate, slosses = _train(small, batches)
    assert small.offload["categorical"].store.ids.size > 0

    big = MeshTrainer(_model(BIG, "hbm"), embed.Adagrad(learning_rate=0.3),
                      mesh=mesh)
    big, bstate, blosses = _train(big, batches)
    np.testing.assert_allclose(slosses, blosses, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("sharded_ckpt", [False, True])
def test_offload_checkpoint_interop(tmp_path, sharded_ckpt):
    """offloaded trainer -> checkpoint -> plain hash trainer (and back): rows
    and optimizer slots survive both directions."""
    batches = _batches(steps=6)
    opt = embed.Adagrad(learning_rate=0.3)
    if sharded_ckpt:
        cached = MeshTrainer(_model(CACHE * 8, "host_cached"), opt,
                             mesh=make_mesh())
    else:
        cached = Trainer(_model(CACHE, "host_cached"), opt)
    cached, cstate, _ = _train(cached, batches)
    path = str(tmp_path / "ck")
    cached.save(cstate, path)

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"] for b in batches]))
    want = _rows(cached, cstate, ids)

    # load into a PLAIN hash trainer (no offload): np_hash_insert path
    plain = Trainer(_model(BIG, "hbm"), embed.Adagrad(learning_rate=0.3))
    pstate = plain.init(batches[0])
    pstate = plain.load(pstate, path)
    np.testing.assert_allclose(_rows(plain, pstate, ids), want,
                               rtol=1e-6, atol=1e-6)

    # load BACK into a fresh offloaded trainer: host-store path
    again = Trainer(_model(CACHE, "host_cached"),
                    embed.Adagrad(learning_rate=0.3))
    astate = again.init(batches[0])
    astate = again.load(astate, path)
    np.testing.assert_allclose(_rows(again, astate, ids), want,
                               rtol=1e-6, atol=1e-6)
    # training continues from the restored store: one more step works
    astate = again.offload_prepare(astate, batches[0])
    astate, m = again.jit_train_step()(astate, batches[0])
    assert np.isfinite(float(m["loss"]))


def test_plain_checkpoint_loads_into_offload(tmp_path):
    """The reverse interop: a normal hash-table checkpoint restores into an
    offloaded trainer through the host store."""
    batches = _batches(steps=5)
    plain = Trainer(_model(BIG, "hbm"), embed.Adagrad(learning_rate=0.3))
    plain, pstate, _ = _train(plain, batches)
    path = str(tmp_path / "ck")
    plain.save(pstate, path)

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"] for b in batches]))
    want = _rows(plain, pstate, ids)

    cached = Trainer(_model(CACHE, "host_cached"),
                     embed.Adagrad(learning_rate=0.3))
    cstate = cached.init(batches[0])
    cstate = cached.load(cstate, path)
    np.testing.assert_allclose(_rows(cached, cstate, ids), want,
                               rtol=1e-6, atol=1e-6)


def test_offload_persist_roundtrip(tmp_path):
    """AsyncPersister with an offloaded trainer: the host store rides the
    persist (decoupled snapshot) and restore rebuilds it."""
    batches = _batches(steps=6)
    opt = embed.Adagrad(learning_rate=0.3)
    trainer = Trainer(_model(CACHE, "host_cached"), opt)
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    root = str(tmp_path / "persists")
    with embed.AsyncPersister(trainer, trainer.model, root,
                              policy=embed.PersistPolicy(every_steps=3)) as p:
        for b in batches:
            state = trainer.offload_prepare(state, b)
            state, _ = step(state, b)
            p.maybe_persist(state)
        p.wait()
        persisted_step = int(state.step)

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"] for b in batches[:persisted_step]]))

    fresh = Trainer(_model(CACHE, "host_cached"),
                    embed.Adagrad(learning_rate=0.3))
    fstate = fresh.init(batches[0])
    from openembedding_tpu.persist import restore_server_model
    fstate = restore_server_model(fstate, fresh.model, root, trainer=fresh)
    assert int(fstate.step) > 0
    got = _rows(fresh, fstate, ids)
    assert np.isfinite(got).all()
    assert (np.abs(got).sum(axis=1) > 0).any()  # trained rows actually restored


def _stack(batches):
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)


def test_offload_train_many_matches_step_loop():
    """The scan-fused path on a host-cached table (union-of-K admission at scan
    entry, packed layout inside) must be BIT-exact vs the per-step
    prepare->step loop on the same stream — the two flagship levers (scan
    fusion and >HBM capacity) compose."""
    batches = _batches(steps=8)
    opt = embed.Adagrad(learning_rate=0.3)

    loop = Trainer(_model(CACHE, "host_cached"), opt)
    loop, lstate, llosses = _train(loop, batches)

    # the scan path admits the union of all K batches at once, so ITS cache
    # must hold the union (the documented sizing rule); the loop path keeps
    # its tiny flush-forced cache — values are exact either way (Constant
    # init + lossless evict/admit round-trips), so the runs stay BIT-equal.
    scan = Trainer(_model(1024, "host_cached"),
                   embed.Adagrad(learning_rate=0.3))
    sstate = scan.init(batches[0])
    sstate, m = scan.offload_train_many(sstate, _stack(batches))
    assert scan.offload  # the two-tier table engaged
    np.testing.assert_array_equal(np.asarray(m["loss"]), np.asarray(llosses))

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"] for b in batches]))
    np.testing.assert_array_equal(_rows(scan, sstate, ids),
                                  _rows(loop, lstate, ids))


def test_offload_train_many_across_windows():
    """Repeated offload_train_many windows (admit union -> scan -> adopt) keep
    the host store authoritative across flushes: equal to the in-HBM oracle."""
    batches = _batches(steps=12, batch=32, seed=9)
    K = 3
    # capacity holds one window's union (<= 192 ids < 0.6*512) but not the
    # stream's cumulative uniques (~700), so inter-window flushes are forced
    scan = Trainer(_model(512, "host_cached"),
                   embed.Adagrad(learning_rate=0.3))
    sstate = scan.init(batches[0])
    slosses = []
    for i in range(0, len(batches), K):
        sstate, m = scan.offload_train_many(sstate, _stack(batches[i:i + K]))
        slosses.extend(np.asarray(m["loss"]).tolist())
    assert scan.offload["categorical"].store.ids.size > 0  # flushes happened

    oracle, ostate, olosses = _train(
        Trainer(_model(BIG, "hbm"), embed.Adagrad(learning_rate=0.3)), batches)
    np.testing.assert_allclose(slosses, olosses, rtol=1e-5, atol=1e-6)

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"] for b in batches]))
    np.testing.assert_allclose(_rows(scan, sstate, ids),
                               _rows(oracle, ostate, ids),
                               rtol=1e-5, atol=1e-6)


def test_mesh_offload_train_many_matches_step_loop():
    """Same composition through the sharded exchange protocol on an 8-device
    mesh: shard_map'd scan over a row-sharded cache, union admission under
    the per-shard admit."""
    mesh = make_mesh()
    batches = _batches(steps=6)
    opt = embed.Adagrad(learning_rate=0.3)

    loop = MeshTrainer(_model(CACHE * 8, "host_cached"), opt, mesh=mesh)
    loop, lstate, llosses = _train(loop, batches)

    scan = MeshTrainer(_model(CACHE * 8, "host_cached"),
                       embed.Adagrad(learning_rate=0.3), mesh=mesh)
    sstate = scan.init(batches[0])
    sstate, m = scan.offload_train_many(sstate, _stack(batches))
    np.testing.assert_allclose(np.asarray(m["loss"]), np.asarray(llosses),
                               rtol=1e-6, atol=1e-7)

    ids = np.unique(np.concatenate(
        [b["sparse"]["categorical"] for b in batches]))
    np.testing.assert_allclose(_rows(scan, sstate, ids),
                               _rows(loop, lstate, ids),
                               rtol=1e-6, atol=1e-7)


def test_raw_train_many_without_prepare_fails_fast():
    """An UNPREPARED cache must not silently train initializer rows over the
    store: the first (tracing) call of raw train_many raises with guidance;
    after a prepare, the same call works."""
    batches = _batches(steps=2)
    tr = Trainer(_model(1024, "host_cached"), embed.Adagrad(learning_rate=0.3))
    state = tr.init(batches[0])
    stacked = _stack(batches)
    with pytest.raises(ValueError, match="offload_train_many"):
        tr.jit_train_many()(state, stacked)
    state = tr.offload_prepare(state, stacked)
    state, m = tr.jit_train_many()(state, stacked)
    assert np.isfinite(np.asarray(m["loss"])).all()
