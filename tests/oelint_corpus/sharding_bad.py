"""Planted sharding-registry violations for the sharding pass.

Every marked line must be caught; the registry sites WITHOUT a marker
(the reference spellings) must not be flagged.
"""

from jax.sharding import PartitionSpec as P


class EmbeddingTableState:  # stand-in: the pass matches by constructor name
    def __init__(self, **kw):
        self.kw = kw


def reference_spec(axis):
    # the reference spelling: row-sharded weights/slots/keys, replicated
    # overflow — this site defines the registry entry and is NOT flagged
    return EmbeddingTableState(
        weights=P(axis),
        slots={k: P(axis) for k in ("acc",)},
        keys=P(axis),
        overflow=P(),
    )


def conflicting_spec(axis):
    return EmbeddingTableState(
        weights=P(),  # PLANT: same leaf bound replicated vs sharded above
        slots={k: P() for k in ("acc",)},  # PLANT: slot leaf disagrees too
        keys=P(axis),
        overflow=P(),
    )


def untrimmed_spelling(axis):
    # placement-identical to P(axis) but a DIFFERENT jit cache key
    return P(axis, None)  # PLANT: trailing-None spelling drift


def ternary_conflict(axis, serving):
    return EmbeddingTableState(
        weights=P(axis),
        slots={},
        keys=P(axis),
        overflow=P(axis) if serving else P(axis),  # PLANT: ternary arms disagree with registry
    )


def fine_unresolvable(dims, axis):
    # computed dims are skipped, never guessed: no finding here
    return P(*dims), P(axis)
