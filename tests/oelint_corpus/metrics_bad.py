"""oelint corpus: planted metric-name violations (parsed, never imported)."""

from openembedding_tpu.utils import metrics


def planted_metric_names():
    metrics.observe("skwe.hot_id", 1)  # PLANT: unknown-group-typo
    metrics.observe("justonename", 1)  # PLANT: not-dotted
    metrics.observe("exchange.user_table.ms", 1)  # PLANT: instance-in-name
    metrics.observe("serving.shard3.rows", 1)  # PLANT: instance-number
    with metrics.vtimer("nosuchgroup", "step"):  # PLANT: unknown-span-group
        pass
    metrics.observe(
        "memory.bytes", 1.0, "gauge",
        labels={"request_id": "ab12cd"})  # PLANT: unbounded-label-key
    metrics.observe(
        "serving.predict.ms", 1.0, "hist",
        labels={"step": "31337"})  # PLANT: unbounded-label-key-step
