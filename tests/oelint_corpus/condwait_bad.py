"""oelint corpus: planted cond-wait violations (parsed, never imported).

Condition discipline: wait in a predicate loop under the lock, notify under
the lock. The clean variants pin the accepted idioms (while-loop wait,
wait_for, notify inside the with, waiting via the underlying-lock alias).
"""

import threading


class PlantedCondWait:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._ready = False
        self._stop = False

    # -- wait must sit in a while-predicate loop under the lock -------------

    def bad_bare_wait(self):
        with self._cv:
            self._cv.wait()  # PLANT: wait-no-loop

    def bad_if_guarded_wait(self):
        with self._cv:
            if not self._ready:
                self._cv.wait()  # PLANT: wait-if-not-while

    def bad_wait_without_lock(self):
        self._cv.wait()  # PLANT: wait-outside-lock

    def good_predicate_loop(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()

    def good_timed_tick_loop(self):
        with self._cv:
            while not self._stop:
                self._cv.wait(timeout=0.05)

    def good_wait_for(self):
        with self._cv:
            self._cv.wait_for(lambda: self._ready)

    def good_wait_under_lock_alias(self):
        with self._lock:  # holding the underlying lock holds the condition
            while not self._ready:
                self._cv.wait()

    # -- notify must run with the lock held ---------------------------------

    def bad_unlocked_notify(self):
        self._ready = True
        self._cv.notify()  # PLANT: notify-outside-lock

    def bad_unlocked_notify_all(self):
        self._cv.notify_all()  # PLANT: notify-all-outside-lock

    def good_locked_notify(self):
        with self._cv:
            self._ready = True
            self._cv.notify_all()


class EventIsNotACondition:
    """Event.wait is level-triggered and loop-free by design: none of the
    condition rules apply to it."""

    def __init__(self):
        self._ev = threading.Event()

    def good_event_wait(self):
        self._ev.wait(timeout=0.1)  # not a Condition: never a finding
