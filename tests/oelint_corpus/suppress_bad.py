"""oelint corpus: suppression policy — a reasoned pragma silences the pass;
a BARE one still silences it but is itself flagged (zero-bare policy)."""

import jax.numpy as jnp


# oelint: jit-entry
def suppressed_hazards(x):
    s = jnp.sum(x)
    if s > 0:  # oelint: disable=trace-hazard -- corpus: reasoned pragma, pass must stay silent
        x = x + 1
    if s < 0:  # oelint: disable=trace-hazard
        x = x - 1  # the line above is a BARE suppression: flagged
    return x
