"""oelint corpus: planted thread-lifecycle violations (parsed, never
imported).

Every stored or started thread needs a reachable join. The clean classes
pin the accepted idioms: tuple-swap join in stop(), join via a stop helper
reached from close(), threads returned/stored/handed off.
"""

import threading


class PlantedNoStopMethod:
    """Stores a worker but has NO stop/close at all (the pre-round-19
    SkewMonitor shape)."""

    def __init__(self):
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)  # PLANT: no-stop-method
        self._thread.start()

    def _run(self):
        pass


class PlantedStopWithoutJoin:
    """Has a stop() — but it only flips the flag and never joins."""

    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)  # PLANT: stop-never-joins
        self._thread.start()

    def stop(self):
        self._stop.set()  # forgot: self._thread.join()

    def _run(self):
        pass


class PlantedFireAndForget:
    def spawn_anonymous(self, server):
        threading.Thread(target=server.shutdown, daemon=True).start()  # PLANT: anonymous-fire-and-forget

    def spawn_local(self):
        t = threading.Thread(target=self._work)  # PLANT: local-fire-and-forget
        t.start()

    def _work(self):
        pass


class CleanTupleSwap:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join()

    def _run(self):
        pass


class CleanJoinViaHelper:
    """close() reaches the join transitively through self._halt()."""

    def __init__(self):
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def close(self):
        self._halt()

    def _halt(self):
        if self._thread is not None:
            self._thread.join()

    def _run(self):
        pass


class CleanHandoff:
    def make_worker(self):
        t = threading.Thread(target=self._run)
        t.start()
        return t  # returned: the caller owns the join

    def lend_worker(self, registry):
        t = threading.Thread(target=self._run)
        t.start()
        registry.adopt(t)  # handed off: the registry owns it

    def joined_locally(self):
        t = threading.Thread(target=self._run)
        t.start()
        t.join()

    def _run(self):
        pass
