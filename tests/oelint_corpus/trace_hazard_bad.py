"""oelint corpus: planted trace-hazard violations (parsed by the lint pass,
NEVER imported/executed). Each PLANT-marked line must produce a finding —
tests/test_oelint.py asserts the pass catches every one."""

import jax
import jax.numpy as jnp


def _helper(x, cfg):
    return x


_jitted = jax.jit(_helper, static_argnums=(1,))


# oelint: jit-entry
def planted_trace_hazards(x):
    s = jnp.sum(x)
    if s > 0:  # PLANT: if-on-traced
        x = x + 1
    t = jnp.mean(x)
    while t > 0:  # PLANT: while-on-traced
        t = t - 1
    n = int(jnp.max(x))  # PLANT: int-on-traced
    f = float(s)  # PLANT: float-on-traced
    b = bool(jnp.any(x))  # PLANT: bool-on-traced
    y = 1 if jnp.any(x) else 0  # PLANT: ternary-on-traced
    assert jnp.all(x > 0)  # PLANT: assert-on-traced
    idx = jnp.nonzero(x)  # PLANT: data-dep-no-size
    k = idx[0].shape  # PLANT: shape-of-data-dep
    total = 0
    for key in {"a", "b", "c"}:  # PLANT: set-iteration
        total += len(key)
    u = jnp.unique(x, size=4)  # size= given: NOT a finding
    return n, f, b, y, k, total, u


def planted_static_args(x):
    bad1 = _jitted(x, [1, 2, 3])  # PLANT: unhashable-static-list
    bad2 = _jitted(x, 0.5)  # PLANT: float-static
    ok = _jitted(x, 7)  # hashable int: NOT a finding
    return bad1, bad2, ok
