"""oelint corpus: planted atomicity violations (parsed, never imported).

Both check-then-act shapes the pass exists for, next to the correct
versions of the same code so the clean idioms are pinned as non-findings.
"""

import threading


class PlantedAtomicity:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._groups = {}  # guarded-by: self._lock
        self.version = None  # guarded-by: self._lock
        self._count = 0  # guarded-by: self._lock

    # -- shape A: locked read -> tainted local -> branch -> locked write ----

    def bad_split_leader(self, key, entry):
        with self._lock:
            group = self._groups.setdefault(key, [])
            group.append(entry)
            leader = len(group) == 1
        if leader:  # PLANT: split-check-then-act
            with self._lock:
                self._groups.pop(key, None)

    def bad_split_snapshot(self):
        with self._lock:
            n = self._count
        if n == 0:  # PLANT: stale-snapshot-act
            with self._lock:
                self._count = 1

    def good_split_held_across(self, key):
        with self._lock:
            group = self._groups.setdefault(key, [])
            if len(group) == 1:  # check and act under ONE critical section
                self._groups.pop(key, None)

    def good_unrelated_branch(self):
        with self._lock:
            n = self._count
        if n > 10:  # decision acts on nothing guarded: not a finding
            return n
        return 0

    # -- shape B: unlocked guarded read guarding a locked write -------------

    def bad_double_checked_seed(self, head):
        if self.version is None:  # PLANT: unlocked-guard-of-locked-write
            with self._lock:
                self.version = int(head)

    def bad_unlocked_count_guard(self):
        while self._count < 4:  # PLANT: unlocked-loop-guard
            with self._lock:
                self._count += 1

    def good_check_inside_lock(self, head):
        with self._lock:
            if self.version is None:  # re-checked under the lock: clean
                self.version = int(head)

    def good_condition_alias(self):
        with self._cond:  # Condition(self._lock) alias holds the lock
            if self.version is None:
                self.version = 0
