"""oelint corpus: idiomatic code — every pass must report ZERO findings.
The shapes here mirror the real tree's legal patterns (static-shape
branches, sorted iteration, one-device_get hot paths, locked writes)."""

import threading

import jax
import jax.numpy as jnp


# oelint: jit-entry
def clean_jit_fn(x, spec):
    s = jnp.sum(x)
    y = jnp.where(s > 0, x, -x)  # data-dependent branch via where
    if x.shape[0] > 4:  # .shape is static under jit
        y = y[:4]
    if spec is None:  # identity test: static Python decision
        y = y * 2
    for key in sorted({"b", "a"}):  # sorted set: deterministic order
        y = y + len(key)
    u = jnp.unique(x, size=4)  # static output shape via size=
    return y, u


# oelint: hot-path
def clean_hot_path(stats):
    host = jax.device_get(dict(stats))  # the ONE allowed per-step get
    return {k: float(v) for k, v in host.items()}


class CleanLocked:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: self._lock

    def bump(self):
        with self._lock:
            self._n += 1


def clean_labeled_metrics():
    from openembedding_tpu.utils import metrics
    # registered group + registered label keys: the metrics pass stays quiet
    metrics.observe("memory.bytes", 4096.0, "gauge",
                    labels={"component": "weights", "table": "user"})
    metrics.observe("history.dropped_series", 1.0, "gauge")
