"""oelint corpus: planted lockset violations (parsed, never imported)."""

import threading


class PlantedLockset:
    shared_registry = {}  # PLANT: class-mutable-dict
    shared_list = []  # PLANT: class-mutable-list

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._state = 0  # guarded-by: self._lock
        self._items = {}  # guarded-by: self._lock

    def good_write(self):
        with self._lock:
            self._state = 1

    def good_write_via_condition(self):
        with self._cond:  # Condition(self._lock) alias: NOT a finding
            self._state = 2

    def bad_write(self):
        self._state = 3  # PLANT: unguarded-write

    def bad_subscript_write(self, key):
        self._items[key] = 1  # PLANT: unguarded-subscript-write

    def bad_tuple_write(self):
        ok, self._state = True, 4  # PLANT: unguarded-tuple-write

    def bad_augmented(self):
        self._state += 1  # PLANT: unguarded-augassign
