"""oelint corpus: planted lockset violations (parsed, never imported)."""

import threading


class PlantedLockset:
    shared_registry = {}  # PLANT: class-mutable-dict
    shared_list = []  # PLANT: class-mutable-list

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._state = 0  # guarded-by: self._lock
        self._items = {}  # guarded-by: self._lock

    def good_write(self):
        with self._lock:
            self._state = 1

    def good_write_via_condition(self):
        with self._cond:  # Condition(self._lock) alias: NOT a finding
            self._state = 2

    def bad_write(self):
        self._state = 3  # PLANT: unguarded-write

    def bad_subscript_write(self, key):
        self._items[key] = 1  # PLANT: unguarded-subscript-write

    def bad_tuple_write(self):
        ok, self._state = True, 4  # PLANT: unguarded-tuple-write

    def bad_augmented(self):
        self._state += 1  # PLANT: unguarded-augassign


class PlantedOrdering:
    """Two locks taken in opposite orders on two paths: the classic AB/BA
    deadlock, plus a single-thread re-acquire of a non-reentrant Lock."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab_path(self):
        with self._a:
            with self._b:  # PLANT: lock-order-cycle (a -> b)
                pass

    def ba_path(self):
        with self._b:
            with self._a:  # PLANT: lock-order-cycle (b -> a)
                pass

    def helper_taking_b(self):
        with self._b:
            pass

    def reacquire(self):
        with self._a:
            with self._a:  # PLANT: non-reentrant re-acquire
                pass
