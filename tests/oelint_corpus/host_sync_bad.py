"""oelint corpus: planted host-sync violations in a `# oelint: hot-path`
function (parsed by the lint pass, never imported)."""

import jax
import jax.numpy as jnp
import numpy as np


# oelint: hot-path
def planted_host_syncs(state, batch):
    host = jax.device_get(state)  # first get: inside the budget of 1...
    again = jax.device_get(batch)  # PLANT: second-device-get
    jnp.sum(batch).block_until_ready()  # PLANT: block-until-ready
    copied = np.asarray(jnp.mean(batch))  # PLANT: np-asarray-of-device
    scalar = float(jnp.max(batch))  # PLANT: float-of-device
    fine = float(host["loss"])  # post-device_get host value: NOT a finding
    return again, copied, scalar, fine


# oelint: hot-path device_get=0
def planted_zero_budget(state):
    return jax.device_get(state)  # PLANT: device-get-over-zero-budget
