"""Planted SPMD-divergence violations for the spmd-divergence pass.

Every marked line must be caught. The uniform controls at the bottom
(process_count branch, step-driven cadence) must stay clean.
"""

import time

import jax


def branch_on_process_index(x, axis):
    if jax.process_index() == 0:
        x = x + jax.lax.psum(x, axis)  # PLANT: collective under a per-process branch
    return x


def wall_clock_gate(x, axis, last):
    if time.monotonic() - last > 5.0:
        return jax.lax.all_gather(x, axis)  # PLANT: wall-clock-gated collective
    return x


def wall_clock_through_a_helper(x):
    # divergence must propagate through the helper's return value
    return time.monotonic() > 0


def gated_by_helper(x, axis):
    if wall_clock_through_a_helper(x):
        return jax.lax.pmax(x, axis)  # PLANT: divergent helper return gates a collective
    return x


def early_exit_then_collective(x, axis):
    pidx = jax.process_index()
    if pidx != 0:
        return x
    return jax.lax.psum(x, axis)  # PLANT: collective after a divergent early return


def set_ordered_collectives(tables, axis):
    out = []
    for name in set(tables):
        out.append(jax.lax.psum(tables[name], axis))  # PLANT: set iteration orders a collective sequence
    return out


def per_shard_view_gate(arr, x, axis):
    if arr.addressable_shards[0].data.sum() > 0:
        return jax.lax.psum(x, axis)  # PLANT: per-shard device view gates a collective
    return x


# -- uniform controls: none of these may be flagged --------------------------


def uniform_process_count(x, axis):
    # process_count is identical on every process: branching on it is fine
    if jax.process_count() > 1:
        return jax.lax.psum(x, axis)
    return x


def step_driven_cadence(x, axis, step):
    # step counters are lockstep-uniform: the canonical divergence-free gate
    if step % 100 == 0:
        return jax.lax.psum(x, axis)
    return x


def divergent_branch_without_collectives(path):
    # process-0-only host work with no rendezvous inside or after: fine
    if jax.process_index() == 0:
        with open(path, "w") as f:
            f.write("ok")
