"""oelint + runtime guards acceptance (ISSUE 6).

- every pass catches every `# PLANT:`-marked violation in its corpus file
  (tests/oelint_corpus/), and reports ZERO findings on the clean corpus;
- suppression policy: a reasoned pragma silences a pass, a bare one still
  silences it but is itself flagged;
- the REAL tree is clean under the file-scanning passes (the triage
  satellite: fixes landed, false positives carry reasoned pragmas);
- the hlo-budget pass detects a deliberately added collective and the
  checked-in budget matches the current tree (fused config compiled live);
- utils/guards: assert_no_recompile passes on re-invocation with the same
  shapes, trips on a forced shape change (both plain and pre-jitted forms),
  and trace_counter counts new compilations.
"""

import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.oelint import run_passes  # noqa: E402
from tools.oelint.core import SourceFile  # noqa: E402
from tools.oelint.passes import (hlo_budget, host_sync, lockset,  # noqa: E402
                                 metrics as metrics_pass, trace_hazard)

CORPUS = "tests/oelint_corpus"


def corpus_file(name: str) -> SourceFile:
    return SourceFile(ROOT, f"{CORPUS}/{name}")


def plant_lines(sf: SourceFile) -> set:
    return {i for i, line in enumerate(sf.lines, 1) if "# PLANT:" in line}


def assert_catches_all_plants(pass_mod, sf: SourceFile):
    findings = pass_mod.run([sf], ROOT)
    hit = {f.line for f in findings}
    missed = plant_lines(sf) - hit
    assert not missed, (
        f"{pass_mod.NAME} missed planted violations at "
        f"{sorted(missed)}: " + "\n".join(map(str, findings)))
    assert all(f.pass_name == pass_mod.NAME for f in findings)


# ---------------------------------------------------------------------------
# corpus: every pass fires on its planted violations, none on clean code
# ---------------------------------------------------------------------------


def test_trace_hazard_catches_every_plant():
    assert_catches_all_plants(trace_hazard, corpus_file("trace_hazard_bad.py"))


def test_host_sync_catches_every_plant():
    assert_catches_all_plants(host_sync, corpus_file("host_sync_bad.py"))


def test_lockset_catches_every_plant():
    assert_catches_all_plants(lockset, corpus_file("lockset_bad.py"))


def test_metrics_catches_every_plant():
    assert_catches_all_plants(metrics_pass, corpus_file("metrics_bad.py"))


def test_clean_corpus_is_clean():
    sf = corpus_file("clean.py")
    for pass_mod in (trace_hazard, host_sync, lockset, metrics_pass):
        findings = pass_mod.run([sf], ROOT)
        assert not findings, (pass_mod.NAME, list(map(str, findings)))
    assert sf.bare_suppressions() == []


def test_suppression_needs_a_reason():
    sf = corpus_file("suppress_bad.py")
    # both hazards are suppressed (reasoned or not): the pass stays silent
    assert trace_hazard.run([sf], ROOT) == []
    # ...but the reasonless pragma is itself a finding
    bare = sf.bare_suppressions()
    assert len(bare) == 1
    assert "bare suppression" in bare[0].message
    assert bare[0].pass_name == "suppression"


def test_tree_is_clean_under_file_passes():
    """The triage satellite's regression pin: the real tree stays green
    under every file-scanning pass (real findings fixed, false positives
    carry reasoned pragmas — zero bare suppressions anywhere)."""
    findings, _ = run_passes(["trace-hazard", "host-sync", "lockset",
                              "metrics"])
    assert findings == [], "\n".join(map(str, findings))


# ---------------------------------------------------------------------------
# hlo-budget: the compiled collective set is pinned per config
# ---------------------------------------------------------------------------


def test_hlo_budget_compare_reports_readable_diffs():
    budget = {"configs": {"fused_fp32": {"all_to_all": 3, "all_reduce": 17,
                                         "wire_bytes_per_step": 32256}}}
    same = {"fused_fp32": {"all_to_all": 3, "all_reduce": 17,
                           "wire_bytes_per_step": 32256}}
    assert hlo_budget.compare(same, budget) == []
    worse = {"fused_fp32": {"all_to_all": 4, "all_reduce": 17,
                            "wire_bytes_per_step": 40000}}
    msgs = [f.message for f in hlo_budget.compare(worse, budget)]
    assert any("all-to-all" in m and "ADDED" in m for m in msgs)
    assert any("bytes/step grew" in m for m in msgs)
    # a missing budget file is itself a finding, not a silent pass
    assert hlo_budget.compare(same, None)
    # an unknown config demands a budget regen
    extra = {"new_cfg": {"all_to_all": 1}}
    assert any("not in the checked-in budget" in f.message
               for f in hlo_budget.compare(extra, budget))


def test_hlo_budget_matches_tree_and_detects_planted_collective():
    """Acceptance: the checked-in budget matches the CURRENT tree for the
    fused config (fresh clone -> `make lint` green), and a deliberately
    added collective on that pinned path is detected."""
    import jax

    budget = hlo_budget.load_budget(ROOT)
    assert budget is not None, "tools/oelint/hlo_budget.json not checked in"
    cfg = next(c for c in hlo_budget.CONFIGS if c["name"] == "fused_fp32")

    trainer, batch = hlo_budget.make_trainer(cfg)
    clean = {"fused_fp32": hlo_budget.measure_trainer(trainer, batch)}
    assert hlo_budget.compare(clean, budget) == [], (
        "checked-in budget is stale vs the tree: run "
        "`python -m tools.oelint --update-budget`")

    # plant one extra collective on the pinned path: an extra pmean of the
    # loss is numerically inert (loss is replicated) but compiles to one
    # more all-reduce — exactly the regression class the pass exists for
    planted, batch2 = hlo_budget.make_trainer(cfg)
    orig = planted.reduce_metrics

    def with_extra_collective(m):
        out = orig(m)
        out["loss"] = jax.lax.pmean(out["loss"], planted.axis)
        return out

    planted.reduce_metrics = with_extra_collective
    measured = {"fused_fp32": hlo_budget.measure_trainer(planted, batch2)}
    msgs = [f.message for f in hlo_budget.compare(measured, budget)]
    assert any("all-reduce" in m and "ADDED" in m for m in msgs), msgs


def test_hlo_budget_covers_acceptance_matrix():
    """The checked-in budget pins per-table, fused-group, hot-on/off and all
    three wire modes (the ISSUE 6 acceptance list) — by name."""
    budget = hlo_budget.load_budget(ROOT)
    names = set(budget["configs"])
    assert {"per_table_fp32", "fused_fp32", "fused_bf16", "fused_int8",
            "fused_fp32_hot"} <= names
    # and the pins are non-degenerate: fused < per-table a2a count, hot adds
    # all-reduces, quantized wire ships fewer bytes
    cfgs = budget["configs"]
    assert cfgs["fused_fp32"]["all_to_all"] < \
        cfgs["per_table_fp32"]["all_to_all"]
    assert cfgs["fused_fp32_hot"]["all_reduce"] > \
        cfgs["fused_fp32"]["all_reduce"]
    assert cfgs["fused_int8"]["wire_bytes_per_step"] < \
        cfgs["fused_bf16"]["wire_bytes_per_step"] < \
        cfgs["fused_fp32"]["wire_bytes_per_step"]


# ---------------------------------------------------------------------------
# utils/guards: the never-re-jit rule as a runtime assertion
# ---------------------------------------------------------------------------


def test_assert_no_recompile_plain_callable():
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import (RecompileError,
                                                assert_no_recompile)
    calls = []

    @assert_no_recompile
    def fn(x):
        calls.append(1)
        return x * 2

    np.testing.assert_array_equal(fn(jnp.ones((4,))), 2 * np.ones(4))
    fn(jnp.ones((4,)))  # same shape: cached, no retrace
    assert fn.trace_count() == 1
    with pytest.raises(RecompileError, match="traced 2 times"):
        fn(jnp.ones((5,)))  # forced shape change


def test_assert_no_recompile_prejitted_fn():
    import jax
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import (RecompileError,
                                                assert_no_recompile)
    step = jax.jit(lambda x: x + 1)
    guarded = assert_no_recompile(step, label="step")
    guarded(jnp.ones((2, 3)))
    guarded(jnp.ones((2, 3)))  # re-invocation, same shapes: fine
    with pytest.raises(RecompileError, match="new programs"):
        guarded(jnp.ones((2, 4)))


def test_assert_no_recompile_multi_mode_budget():
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import (RecompileError,
                                                assert_no_recompile)
    fn = assert_no_recompile(lambda x: x, max_traces=2)
    fn(jnp.ones((1,)))
    fn(jnp.ones((2,)))  # second mode: inside the budget
    with pytest.raises(RecompileError):
        fn(jnp.ones((3,)))


def test_trace_counter_counts_new_compilations():
    import jax
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import trace_counter
    fn = jax.jit(lambda x: x - 1)
    fn(jnp.ones((2,)))  # warmup outside the window
    with trace_counter(fn) as tc:
        fn(jnp.ones((2,)))
        assert tc.new_traces == 0
        fn(jnp.ones((9,)))
        assert tc.new_traces == 1
    assert tc.new_traces == 1  # still readable after exit
