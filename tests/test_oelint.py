"""oelint + runtime guards acceptance (ISSUEs 6 and 11).

- every pass catches every `# PLANT:`-marked violation in its corpus file
  (tests/oelint_corpus/), and reports ZERO findings on the clean corpus;
- suppression policy: a reasoned pragma silences a pass, a bare one still
  silences it but is itself flagged;
- the REAL tree is clean under the file-scanning passes (the triage
  satellite: fixes landed, false positives carry reasoned pragmas);
- the hlo-budget pass detects a deliberately added collective and the
  checked-in budget matches the current tree (fused config compiled live);
- implicit-reshard: a deliberately mismatched out_sharding makes GSPMD
  insert an unattributed reshard collective, and the detector fails it;
  explicitly traced collectives always attribute and stay clean;
- utils/guards: assert_no_recompile passes on re-invocation with the same
  shapes, trips on a forced shape change (both plain and pre-jitted forms),
  and trace_counter counts new compilations;
- collective_fingerprint is deterministic, program/shape-sensitive, and
  stays pinned across hot-row refresh, cold-tail migration, and a full
  placement-controller cycle (the SPMD contract as a runtime assertion).
"""

import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.oelint import run_passes  # noqa: E402
from tools.oelint.core import SourceFile  # noqa: E402
from tools.oelint.passes import (atomicity, condwait,  # noqa: E402
                                 hlo_budget, host_sync, implicit_reshard,
                                 lifecycle, lockset,
                                 metrics as metrics_pass, sharding,
                                 spmd_divergence, trace_hazard)

CORPUS = "tests/oelint_corpus"


def corpus_file(name: str) -> SourceFile:
    return SourceFile(ROOT, f"{CORPUS}/{name}")


def plant_lines(sf: SourceFile) -> set:
    return {i for i, line in enumerate(sf.lines, 1) if "# PLANT:" in line}


def assert_catches_all_plants(pass_mod, sf: SourceFile):
    findings = pass_mod.run([sf], ROOT)
    hit = {f.line for f in findings}
    missed = plant_lines(sf) - hit
    assert not missed, (
        f"{pass_mod.NAME} missed planted violations at "
        f"{sorted(missed)}: " + "\n".join(map(str, findings)))
    assert all(f.pass_name == pass_mod.NAME for f in findings)


# ---------------------------------------------------------------------------
# corpus: every pass fires on its planted violations, none on clean code
# ---------------------------------------------------------------------------


def test_trace_hazard_catches_every_plant():
    assert_catches_all_plants(trace_hazard, corpus_file("trace_hazard_bad.py"))


def test_host_sync_catches_every_plant():
    assert_catches_all_plants(host_sync, corpus_file("host_sync_bad.py"))


def test_lockset_catches_every_plant():
    assert_catches_all_plants(lockset, corpus_file("lockset_bad.py"))


def test_atomicity_catches_every_plant():
    assert_catches_all_plants(atomicity, corpus_file("atomicity_bad.py"))


def test_atomicity_clean_idioms_stay_clean():
    """check+act under one critical section, re-check inside the lock, and
    Condition aliases are never flagged: exactly the plants fire."""
    sf = corpus_file("atomicity_bad.py")
    findings = atomicity.run([sf], ROOT)
    assert {f.line for f in findings} == plant_lines(sf), \
        "\n".join(map(str, findings))


def test_condwait_catches_every_plant():
    assert_catches_all_plants(condwait, corpus_file("condwait_bad.py"))


def test_condwait_clean_idioms_stay_clean():
    """while-predicate waits (timed included), wait_for, locked notify, the
    underlying-lock alias, and Event.wait are never flagged."""
    sf = corpus_file("condwait_bad.py")
    findings = condwait.run([sf], ROOT)
    assert {f.line for f in findings} == plant_lines(sf), \
        "\n".join(map(str, findings))


def test_lifecycle_catches_every_plant():
    assert_catches_all_plants(lifecycle, corpus_file("lifecycle_bad.py"))


def test_lifecycle_clean_idioms_stay_clean():
    """tuple-swap join, join via a stop helper, and returned/handed-off/
    locally-joined threads are never flagged."""
    sf = corpus_file("lifecycle_bad.py")
    findings = lifecycle.run([sf], ROOT)
    assert {f.line for f in findings} == plant_lines(sf), \
        "\n".join(map(str, findings))


def test_metrics_catches_every_plant():
    assert_catches_all_plants(metrics_pass, corpus_file("metrics_bad.py"))


def test_sharding_catches_every_plant():
    assert_catches_all_plants(sharding, corpus_file("sharding_bad.py"))


def test_sharding_reference_sites_stay_clean():
    """The registry's agreeing/reference spellings are never flagged — only
    the disagreeing minority sites are."""
    sf = corpus_file("sharding_bad.py")
    findings = sharding.run([sf], ROOT)
    assert {f.line for f in findings} == plant_lines(sf), \
        "\n".join(map(str, findings))


def test_spmd_divergence_catches_every_plant():
    assert_catches_all_plants(spmd_divergence,
                              corpus_file("spmd_divergence_bad.py"))


def test_spmd_divergence_uniform_controls_stay_clean():
    """process_count branches, step-driven cadences, and collective-free
    process-0 work are uniform: exactly the plants fire, nothing else."""
    sf = corpus_file("spmd_divergence_bad.py")
    findings = spmd_divergence.run([sf], ROOT)
    assert {f.line for f in findings} == plant_lines(sf), \
        "\n".join(map(str, findings))


def test_clean_corpus_is_clean():
    sf = corpus_file("clean.py")
    for pass_mod in (trace_hazard, host_sync, lockset, atomicity, condwait,
                     lifecycle, metrics_pass, sharding, spmd_divergence):
        findings = pass_mod.run([sf], ROOT)
        assert not findings, (pass_mod.NAME, list(map(str, findings)))
    assert sf.bare_suppressions() == []


def test_suppression_needs_a_reason():
    sf = corpus_file("suppress_bad.py")
    # both hazards are suppressed (reasoned or not): the pass stays silent
    assert trace_hazard.run([sf], ROOT) == []
    # ...but the reasonless pragma is itself a finding
    bare = sf.bare_suppressions()
    assert len(bare) == 1
    assert "bare suppression" in bare[0].message
    assert bare[0].pass_name == "suppression"


def test_tree_is_clean_under_file_passes():
    """The triage satellite's regression pin: the real tree stays green
    under every file-scanning pass (real findings fixed, false positives
    carry reasoned pragmas — zero bare suppressions anywhere)."""
    findings, _ = run_passes(["trace-hazard", "host-sync", "lockset",
                              "atomicity", "cond-wait", "thread-lifecycle",
                              "metrics", "sharding", "spmd-divergence"])
    assert findings == [], "\n".join(map(str, findings))


# ---------------------------------------------------------------------------
# hlo-budget: the compiled collective set is pinned per config
# ---------------------------------------------------------------------------


def test_hlo_budget_compare_reports_readable_diffs():
    budget = {"configs": {"fused_fp32": {"all_to_all": 3, "all_reduce": 17,
                                         "wire_bytes_per_step": 32256}}}
    same = {"fused_fp32": {"all_to_all": 3, "all_reduce": 17,
                           "wire_bytes_per_step": 32256}}
    assert hlo_budget.compare(same, budget) == []
    worse = {"fused_fp32": {"all_to_all": 4, "all_reduce": 17,
                            "wire_bytes_per_step": 40000}}
    msgs = [f.message for f in hlo_budget.compare(worse, budget)]
    assert any("all-to-all" in m and "ADDED" in m for m in msgs)
    assert any("bytes/step grew" in m for m in msgs)
    # a missing budget file is itself a finding, not a silent pass
    assert hlo_budget.compare(same, None)
    # an unknown config demands a budget regen
    extra = {"new_cfg": {"all_to_all": 1}}
    assert any("not in the checked-in budget" in f.message
               for f in hlo_budget.compare(extra, budget))


def test_hlo_budget_matches_tree_and_detects_planted_collective():
    """Acceptance: the checked-in budget matches the CURRENT tree for the
    fused config (fresh clone -> `make lint` green), and a deliberately
    added collective on that pinned path is detected."""
    import jax

    budget = hlo_budget.load_budget(ROOT)
    assert budget is not None, "tools/oelint/hlo_budget.json not checked in"
    cfg = next(c for c in hlo_budget.CONFIGS if c["name"] == "fused_fp32")

    trainer, batch = hlo_budget.make_trainer(cfg)
    clean = {"fused_fp32": hlo_budget.measure_trainer(trainer, batch)}
    assert hlo_budget.compare(clean, budget) == [], (
        "checked-in budget is stale vs the tree: run "
        "`python -m tools.oelint --update-budget`")

    # plant one extra collective on the pinned path: an extra pmean of the
    # loss is numerically inert (loss is replicated) but compiles to one
    # more all-reduce — exactly the regression class the pass exists for
    planted, batch2 = hlo_budget.make_trainer(cfg)
    orig = planted.reduce_metrics

    def with_extra_collective(m):
        out = orig(m)
        out["loss"] = jax.lax.pmean(out["loss"], planted.axis)
        return out

    planted.reduce_metrics = with_extra_collective
    measured = {"fused_fp32": hlo_budget.measure_trainer(planted, batch2)}
    msgs = [f.message for f in hlo_budget.compare(measured, budget)]
    assert any("all-reduce" in m and "ADDED" in m for m in msgs), msgs


def test_hlo_budget_covers_acceptance_matrix():
    """The checked-in budget pins per-table, fused-group, hot-on/off and all
    three wire modes (the ISSUE 6 acceptance list) — by name."""
    budget = hlo_budget.load_budget(ROOT)
    names = set(budget["configs"])
    assert {"per_table_fp32", "fused_fp32", "fused_bf16", "fused_int8",
            "fused_fp32_hot"} <= names
    # and the pins are non-degenerate: fused < per-table a2a count, hot adds
    # all-reduces, quantized wire ships fewer bytes
    cfgs = budget["configs"]
    assert cfgs["fused_fp32"]["all_to_all"] < \
        cfgs["per_table_fp32"]["all_to_all"]
    assert cfgs["fused_fp32_hot"]["all_reduce"] > \
        cfgs["fused_fp32"]["all_reduce"]
    assert cfgs["fused_int8"]["wire_bytes_per_step"] < \
        cfgs["fused_bf16"]["wire_bytes_per_step"] < \
        cfgs["fused_fp32"]["wire_bytes_per_step"]


# ---------------------------------------------------------------------------
# implicit-reshard: GSPMD-inserted collectives fail lint
# ---------------------------------------------------------------------------


def test_implicit_reshard_fires_on_planted_gspmd_reshard():
    """Acceptance: a deliberately mismatched out_sharding on a compiled fn
    makes GSPMD insert a reshard collective with NO traced-op attribution —
    and the detector fails lint on it, budget-independent."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from openembedding_tpu.parallel import make_mesh
    mesh = make_mesh()
    axis = mesh.axis_names[0]
    row = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    # input arrives row-sharded, output is demanded replicated: the program
    # asks for NO collective, GSPMD must insert the all-gather itself
    f = jax.jit(lambda x: x * 2.0, in_shardings=(row,), out_shardings=rep)
    text = f.lower(jnp.zeros((8, 4))).compile().as_text()
    planted = hlo_budget.unattributed_collectives(text)
    assert planted, "expected a GSPMD-inserted reshard collective"
    assert all(kind in hlo_budget.COLLECTIVES for kind, _ in planted)

    measured = {"planted_cfg": {
        "unattributed_collectives": len(planted),
        "_unattributed_detail": "; ".join(f"{k} <- {a}"
                                          for k, a in planted)}}
    msgs = [f.message for f in implicit_reshard.findings_for(measured)]
    assert msgs and "GSPMD inserted a reshard" in msgs[0], msgs
    assert all(f.pass_name == implicit_reshard.NAME
               for f in implicit_reshard.findings_for(measured))


def test_implicit_reshard_clean_on_attributed_collectives():
    """Explicitly traced collectives carry their primitive in op_name and
    never count as unattributed (verified live on a compiled psum)."""
    import jax
    import jax.numpy as jnp

    from openembedding_tpu.parallel import make_mesh
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh()
    axis = mesh.axis_names[0]
    f = jax.jit(jax.shard_map(lambda x: jax.lax.psum(x, axis), mesh=mesh,
                              in_specs=P(axis), out_specs=P()))
    text = f.lower(jnp.zeros((8, 4))).compile().as_text()
    assert hlo_budget.count_collectives(text)["all_reduce"] >= 1
    assert hlo_budget.unattributed_collectives(text) == []
    assert implicit_reshard.findings_for(
        {"cfg": {"unattributed_collectives": 0}}) == []


# ---------------------------------------------------------------------------
# utils/guards: the never-re-jit rule as a runtime assertion
# ---------------------------------------------------------------------------


def test_assert_no_recompile_plain_callable():
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import (RecompileError,
                                                assert_no_recompile)
    calls = []

    @assert_no_recompile
    def fn(x):
        calls.append(1)
        return x * 2

    np.testing.assert_array_equal(fn(jnp.ones((4,))), 2 * np.ones(4))
    fn(jnp.ones((4,)))  # same shape: cached, no retrace
    assert fn.trace_count() == 1
    with pytest.raises(RecompileError, match="traced 2 times"):
        fn(jnp.ones((5,)))  # forced shape change


def test_assert_no_recompile_prejitted_fn():
    import jax
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import (RecompileError,
                                                assert_no_recompile)
    step = jax.jit(lambda x: x + 1)
    guarded = assert_no_recompile(step, label="step")
    guarded(jnp.ones((2, 3)))
    guarded(jnp.ones((2, 3)))  # re-invocation, same shapes: fine
    with pytest.raises(RecompileError, match="new programs"):
        guarded(jnp.ones((2, 4)))


def test_assert_no_recompile_multi_mode_budget():
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import (RecompileError,
                                                assert_no_recompile)
    fn = assert_no_recompile(lambda x: x, max_traces=2)
    fn(jnp.ones((1,)))
    fn(jnp.ones((2,)))  # second mode: inside the budget
    with pytest.raises(RecompileError):
        fn(jnp.ones((3,)))


def test_trace_counter_counts_new_compilations():
    import jax
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import trace_counter
    fn = jax.jit(lambda x: x - 1)
    fn(jnp.ones((2,)))  # warmup outside the window
    with trace_counter(fn) as tc:
        fn(jnp.ones((2,)))
        assert tc.new_traces == 0
        fn(jnp.ones((9,)))
        assert tc.new_traces == 1
    assert tc.new_traces == 1  # still readable after exit


# ---------------------------------------------------------------------------
# utils/guards: collective_fingerprint — the SPMD contract as a runtime pin
# ---------------------------------------------------------------------------


def _psum_and_pmax_fns():
    import jax
    from jax.sharding import PartitionSpec as P

    from openembedding_tpu.parallel import make_mesh
    mesh = make_mesh()
    axis = mesh.axis_names[0]
    mk = lambda op: jax.shard_map(  # noqa: E731
        lambda x: op(x, axis), mesh=mesh, in_specs=P(axis), out_specs=P())
    return mk(jax.lax.psum), mk(jax.lax.pmax)


def test_collective_fingerprint_deterministic_and_program_sensitive():
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import (collective_fingerprint,
                                                collective_sequence)
    sum_fn, max_fn = _psum_and_pmax_fns()
    x = jnp.ones((8, 4))
    fp = collective_fingerprint(sum_fn, x)
    assert fp == collective_fingerprint(sum_fn, x)   # pure function of trace
    assert fp != collective_fingerprint(max_fn, x)   # different program
    assert fp != collective_fingerprint(sum_fn, jnp.ones((16, 4)))  # shapes
    seq = collective_sequence(sum_fn, x)
    assert len(seq) == 1 and "psum" in str(seq[0]), seq


def test_assert_collective_fingerprint_pass_and_trip():
    import jax.numpy as jnp

    from openembedding_tpu.utils.guards import (
        CollectiveMismatchError, assert_collective_fingerprint,
        collective_fingerprint)
    sum_fn, max_fn = _psum_and_pmax_fns()
    x = jnp.ones((8, 4))
    pin = collective_fingerprint(sum_fn, x)
    assert_collective_fingerprint(sum_fn, pin, x, label="unit")  # no raise
    with pytest.raises(CollectiveMismatchError) as e:
        assert_collective_fingerprint(max_fn, pin, x, label="unit")
    assert "pmax" in str(e.value)  # the message carries the traced sequence


def test_collective_fingerprint_survives_refresh_and_migration():
    """Acceptance (1/2): hot-row refresh and cold-tail migration on the
    pinned fused placement config are content-only — the traced collective
    sequence of the SAME step function is byte-identical after both."""
    from openembedding_tpu.utils.guards import (assert_collective_fingerprint,
                                                collective_fingerprint)
    cfg = next(c for c in hlo_budget.CONFIGS
               if c["name"] == "fused_fp32_placement")
    tr, batch = hlo_budget.make_trainer(cfg)
    state = tr.init(batch)
    step = tr.jit_train_step(batch, state)
    pin = collective_fingerprint(step, state, batch)

    state, _ = step(state, batch)
    state = tr.refresh_hot_rows(
        state, hot_ids={"a": np.arange(32, dtype=np.int64)})
    assert_collective_fingerprint(step, pin, state, batch,
                                  label="post_refresh")
    state = tr.migrate_rows(
        state, moves={"a": (np.array([97, 193], np.int64),
                            np.array([3, 5], np.int64))})
    assert_collective_fingerprint(step, pin, state, batch,
                                  label="post_migration")


def test_collective_fingerprint_survives_placement_controller_cycle():
    """Acceptance (2/2): a full self-driving placement cycle — prime, then
    controller-decided refreshes/migrations under drifting Zipf traffic —
    never changes the traced collective sequence of the step it drives."""
    import flax.linen as nn
    import jax.numpy as jnp

    import openembedding_tpu as embed
    from openembedding_tpu.model import EmbeddingModel
    from openembedding_tpu.parallel import MeshTrainer, make_mesh
    from openembedding_tpu.placement import (PlacementController,
                                             PlacementPolicy)
    from openembedding_tpu.placement.policy import row_bytes
    from openembedding_tpu.utils.guards import (assert_collective_fingerprint,
                                                collective_fingerprint)
    from openembedding_tpu.utils.sketch import SkewMonitor

    class Tower(nn.Module):
        @nn.compact
        def __call__(self, embedded, dense):
            bias = self.param("bias", nn.initializers.zeros, (1,),
                              jnp.float32)
            return jnp.sum(embedded["a"].astype(jnp.float32),
                           axis=(1, 2)) + bias[0]

    S, B, VOCAB = 8, 32, 1 << 10
    model = EmbeddingModel(Tower(), [embed.Embedding(VOCAB, 8, name="a")])
    rng = np.random.default_rng(3)
    # heavy pool homed on one shard, rotated to another mid-run: forces the
    # controller through refresh AND migration decisions (test_placement's
    # drift pattern, shortened — efficacy is pinned there, not here)
    pool_a = (np.arange(16) * S + 5).astype(np.int64)
    pool_b = (np.arange(16) * S + 3).astype(np.int64)
    batches = []
    for i in range(12):
        pool = pool_a if i < 6 else pool_b
        ids = rng.integers(0, VOCAB, (B, 8)).astype(np.int64)
        ids[:, :4] = pool[rng.integers(0, 16, (B, 4))]
        batches.append({"sparse": {"a": ids.astype(np.int32)},
                        "label": rng.integers(0, 2, (B,)).astype(np.float32)})

    mon = SkewMonitor(k=64, sync=True, decay=0.85)
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="fp32")
    policy = PlacementPolicy(8 * row_bytes(8, 1), mig_rows=32,
                             refresh_cooldown_steps=2, imbalance_target=1.05)
    ctrl = PlacementController(tr, policy, monitor=mon, interval_steps=2)
    for b in batches[:3]:  # warm the sketches so prime() can size
        mon.observe("a", b["sparse"]["a"])
    state = tr.init(batches[0])
    state = ctrl.prime(state)  # the one shape-changing moment — pin AFTER
    step = tr.jit_train_step(batches[0], state)
    pin = collective_fingerprint(step, state, batches[0])

    for i, b in enumerate(batches):
        mon.observe("a", b["sparse"]["a"])
        state, _ = step(state, b)
        state = ctrl.on_step(state, step=i + 1)

    st = ctrl.status()
    actuated = (st["migrations_applied"] >= 1
                or any(v > 0 for v in st["last_refresh_step"].values()))
    assert actuated, st  # the cycle must not be vacuous
    assert_collective_fingerprint(step, pin, state, batches[0],
                                  label="placement_cycle")
