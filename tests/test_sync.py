"""Online model sync tests (`openembedding_tpu/sync/`).

The acceptance battery for the trainer->serving delta stream: a live serving
node follows a training run's committed `delta_<step>` chain with no restart
(publisher feed -> subscriber apply -> RCU servable swap), predictions after
each sync match a from-scratch export of the same step BIT-exactly at fp32
wire (within codec tolerance at bf16/int8), and injected torn/reordered/
dropped deltas leave the node serving the last good version (DEGRADED +
`sync.rollbacks`, zero failed predicts).
"""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

import jax

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.export import StandaloneModel, export_standalone
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.ops import wire as wire_mod
from openembedding_tpu.persist import (IncrementalPersister, PersistPolicy,
                                       list_deltas, list_persists)
from openembedding_tpu.serving import make_server
from openembedding_tpu.sync import (FaultInjector, SyncSubscriber)
from openembedding_tpu.utils import metrics

VOCAB = 1 << 10


# -- wire codec parity --------------------------------------------------------


def test_np_wire_codec_matches_device_codec():
    """The host (numpy) codecs the sync wire uses must agree BIT-for-bit with
    the device (jnp) codecs the exchange uses — one wire semantics."""
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    x = (rng.standard_normal((64, 16)) * 3).astype(np.float32)
    x[5] = 0.0  # all-zero row: int8 scale-0 path
    for fmt in ("fp32", "bf16", "int8"):
        enc = wire_mod.np_encode_rows(x, fmt)
        enc_dev = np.asarray(wire_mod.encode_rows(jnp.asarray(x), fmt))
        if fmt == "bf16":
            enc_dev = enc_dev.view(np.uint16)  # np has no bfloat16
        np.testing.assert_array_equal(enc, enc_dev)
        dec = wire_mod.np_decode_rows(enc, 16, fmt)
        dec_dev = np.asarray(wire_mod.decode_rows(
            wire_mod.encode_rows(jnp.asarray(x), fmt), 16, fmt))
        np.testing.assert_array_equal(dec, dec_dev)
    # fp32 round-trips exactly
    np.testing.assert_array_equal(
        wire_mod.np_decode_rows(wire_mod.np_encode_rows(x, "fp32"), 16,
                                "fp32"), x)


def test_sync_delta_cost_model():
    cost32 = wire_mod.sync_delta_cost({"a": (100, 16)}, "fp32")
    cost16 = wire_mod.sync_delta_cost({"a": (100, 16)}, "bf16")
    cost8 = wire_mod.sync_delta_cost({"a": (100, 16)}, "int8")
    assert cost32["bytes_ids"] == cost16["bytes_ids"] == 800  # ids never shrink
    assert cost32["bytes_rows"] == 100 * 16 * 4
    assert cost16["bytes_rows"] == 100 * 16 * 2
    assert cost8["bytes_rows"] == 100 * (16 + 4)  # + per-row scale lanes
    assert cost32["bytes_total"] > cost16["bytes_total"] > cost8["bytes_total"]


# -- harness ------------------------------------------------------------------


def _train_setup(tmp_path, *, seed=0):
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=seed)
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=8, seed=1))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    root = str(tmp_path / "persist")
    return model, trainer, state, step, batches, root


@pytest.fixture()
def publisher_node(tmp_path):
    """A serving HTTP server (started) whose publisher map tests fill in."""
    srv = make_server(str(tmp_path / "reg_pub"))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv
    srv.shutdown()


@pytest.fixture()
def serving_node(tmp_path):
    srv = make_server(str(tmp_path / "reg_srv"))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv
    for sub in srv.subscribers.values():
        sub.stop()
    srv.shutdown()


def _req(url, method="GET", payload=None, headers=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else {}), dict(e.headers)


# -- publisher feed -----------------------------------------------------------


def test_publisher_feed_versions_and_payloads(tmp_path, publisher_node):
    model, trainer, state, step, batches, root = _train_setup(tmp_path)
    base, srv = publisher_node
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        for b in batches[:3]:  # full base at 1, deltas at 2, 3
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()

        from openembedding_tpu.sync import SyncPublisher
        srv.publishers["m"] = SyncPublisher(root)

        status, feed, hdr = _req(f"{base}/models/m:versions")
        assert status == 200
        assert feed["format"] == "oetpu-sync-v1"
        assert feed["base_step"] == 1 and feed["head_step"] == 3
        assert [d["step"] for d in feed["deltas"]] == [2, 3]
        assert [d["parent"] for d in feed["deltas"]] == [1, 2]
        assert hdr["ETag"] == '"3"'  # ETag = head commit step

        # bounded poll: nothing newer than head -> 304, ETag still present
        status, _, hdr = _req(f"{base}/models/m:versions?after=3&wait_s=0.1")
        assert status == 304 and hdr["ETag"] == '"3"'
        # behind head -> immediate 200
        status, feed, _ = _req(f"{base}/models/m:versions?after=1")
        assert status == 200 and feed["head_step"] == 3

        # delta payloads: meta JSON, table npz (ids exact + wire rows), dense
        status, meta, hdr = _req(f"{base}/models/m/delta/2/meta")
        assert status == 200 and meta["parent"] == 1 and hdr["ETag"] == '"2"'
        import io
        for fmt in ("fp32", "bf16", "int8"):
            with urllib.request.urlopen(
                    f"{base}/models/m/delta/2/table/categorical?wire={fmt}"
                    ) as r:
                z = np.load(io.BytesIO(r.read()))
            assert str(z["fmt"]) == fmt
            assert z["ids"].dtype == np.int64
            rows = wire_mod.np_decode_rows(z["wire"], int(z["dim"]), fmt)
            assert rows.shape == (z["ids"].shape[0], int(z["dim"]))
        with urllib.request.urlopen(f"{base}/models/m/delta/2/dense") as r:
            z = np.load(io.BytesIO(r.read()))
        assert z.files and not any(k.startswith("slots/") for k in z.files)

        # unknown step / table / junk wire format -> 404 / 404 / 400
        assert _req(f"{base}/models/m/delta/99/meta")[0] == 404
        assert _req(f"{base}/models/m/delta/2/table/nope")[0] == 404
        assert _req(f"{base}/models/m/delta/2/table/categorical?wire=xx"
                    )[0] == 400
        # no publisher registered for that sign -> 404
        assert _req(f"{base}/models/other:versions")[0] == 404


# -- the acceptance battery ---------------------------------------------------


def test_online_sync_end_to_end_bit_exact(tmp_path, publisher_node,
                                          serving_node):
    """Trainer commits base + 3 deltas while the serving node answers
    predicts; each delta applies without restart; after each sync the node's
    prediction equals a from-scratch export of the same step bit-exactly
    (fp32 wire); zero failed predicts throughout."""
    model, trainer, state, step, batches, root = _train_setup(tmp_path)
    pub_url, pub_srv = publisher_node
    srv_url, srv = serving_node

    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])
        p.wait()
        export_dir = str(tmp_path / "export")
        export_standalone(state, model, export_dir, model_sign="sync-0")

        from openembedding_tpu.sync import SyncPublisher
        pub_srv.publishers["sync-0"] = SyncPublisher(root)
        srv.manager.load_model("sync-0", export_dir)

        # live predict hammer: runs across every swap below
        stop = threading.Event()
        failures = []
        req_body = {"sparse": {"categorical": np.asarray(
            batches[0]["sparse"]["categorical"]).tolist()},
            "dense": np.asarray(batches[0]["dense"]).tolist()}

        def hammer():
            while not stop.is_set():
                status, out, _ = _req(f"{srv_url}/models/sync-0/predict",
                                      "POST", req_body)
                if status != 200:
                    failures.append(out)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            sub = SyncSubscriber(srv.manager, "sync-0", pub_url)
            assert sub.poll() == 0 and sub.version == 1  # negotiated

            for i, b in enumerate(batches[1:4], start=2):
                state, _ = step(state, b)
                p.maybe_persist(state, batch=b)
                p.wait()
                assert sub.poll() == 1, sub.last_error
                assert sub.state == "IDLE" and sub.version == i

                oracle_dir = str(tmp_path / f"oracle_{i}")
                export_standalone(state, model, oracle_dir)
                oracle = StandaloneModel.load(oracle_dir)
                servable = srv.manager.find_model("sync-0")
                assert servable.step == i
                np.testing.assert_array_equal(
                    np.asarray(servable.predict(batches[0])),
                    np.asarray(oracle.predict(batches[0])))
        finally:
            stop.set()
            t.join(timeout=10)
        assert failures == [], failures[:3]
        assert metrics.Accumulator.get("sync.applied_deltas").value() >= 3


def test_online_sync_quantized_wire_within_tolerance(tmp_path, publisher_node):
    """bf16/int8 subscribers land within codec tolerance of the live rows
    (storage stays fp32; only the feed bytes shrink)."""
    model, trainer, state, step, batches, root = _train_setup(tmp_path)
    pub_url, pub_srv = publisher_node
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])
        p.wait()
        export_dir = str(tmp_path / "export")
        export_standalone(state, model, export_dir, model_sign="q")
        from openembedding_tpu.sync import SyncPublisher
        pub_srv.publishers["q"] = SyncPublisher(root)
        for b in batches[1:4]:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()

    from openembedding_tpu.serving import ModelManager, ModelRegistry
    live = np.asarray(state.tables["categorical"].weights)
    for fmt, tol in (("bf16", 3e-2), ("int8", 6e-2)):
        mgr = ModelManager(ModelRegistry(str(tmp_path / f"reg_{fmt}")))
        mgr.load_model("q", export_dir)
        sub = SyncSubscriber(mgr, "q", pub_url, wire=fmt)
        assert sub.poll() == 3, sub.last_error
        got = np.asarray(mgr.find_model("q").lookup(
            "categorical", np.arange(64, dtype=np.int64)))
        scale = max(1.0, float(np.abs(live[:64]).max()))
        assert np.abs(got - live[:64]).max() <= tol * scale, fmt


class _Truncate(FaultInjector):
    """Chop rows off one table payload — a torn delta."""

    def __init__(self, step):
        self.step = step

    def payload(self, step, payload):
        if step == self.step:
            name, (ids, rows) = next(iter(payload["tables"].items()))
            payload["tables"][name] = (ids, rows[:-1])
        return payload


class _Reorder(FaultInjector):
    def plan(self, steps):
        return steps[::-1]


class _DropMiddle(FaultInjector):
    def plan(self, steps):
        return [s for i, s in enumerate(steps) if i != 1 or len(steps) < 2]


class _Duplicate(FaultInjector):
    def plan(self, steps):
        return steps[:1] + steps


@pytest.mark.parametrize("fault_cls", [_Truncate, _Reorder, _DropMiddle,
                                       _Duplicate])
def test_sync_fault_injection_degrades_gracefully(tmp_path, publisher_node,
                                                  serving_node, fault_cls):
    """Injected torn/reordered/dropped/duplicated deltas: the node keeps
    serving the last good version (DEGRADED, `sync.rollbacks` incremented,
    zero failed predicts), and recovers once the fault clears."""
    model, trainer, state, step, batches, root = _train_setup(tmp_path)
    pub_url, pub_srv = publisher_node
    srv_url, srv = serving_node
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])
        p.wait()
        export_dir = str(tmp_path / "export")
        export_standalone(state, model, export_dir, model_sign="f")
        from openembedding_tpu.sync import SyncPublisher
        pub_srv.publishers["f"] = SyncPublisher(root)
        srv.manager.load_model("f", export_dir)
        for b in batches[1:4]:  # deltas at 2, 3, 4
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()

    faults = (fault_cls(2) if fault_cls is _Truncate else fault_cls())
    sub = SyncSubscriber(srv.manager, "f", pub_url, faults=faults)
    before = metrics.Accumulator.get("sync.rollbacks").value()
    assert sub.poll() == 0  # the guarded tick reports the failed round
    assert sub.state == "DEGRADED"
    assert sub.last_error
    assert metrics.Accumulator.get("sync.rollbacks").value() == before + 1
    # the node still serves the newest version that applied CLEANLY — a
    # consistent prefix, never a torn mix
    servable = srv.manager.find_model("f")
    assert servable.step == sub.version
    prefix = sub.version - 1  # deltas that applied before the fault point
    status, out, _ = _req(f"{srv_url}/models/f/predict", "POST",
                          {"sparse": {"categorical": np.asarray(
                              batches[0]["sparse"]["categorical"]).tolist()},
                           "dense": np.asarray(batches[0]["dense"]).tolist()})
    assert status == 200  # zero failed predicts while degraded

    sub.faults = None  # fault clears -> next poll catches up fully
    assert sub.poll() == 3 - prefix, sub.last_error
    assert sub.state == "IDLE" and sub.version == 4


def test_statusz_shows_degraded_state_and_reason(tmp_path, publisher_node,
                                                 serving_node):
    """Operator surface for the fault path: after an injected torn delta the
    serving node's GET /statusz renders the subscriber's DEGRADED state WITH
    the last DEGRADED reason (and :syncstate carries it as
    `last_degraded_reason`), and the reason survives recovery."""
    model, trainer, state, step, batches, root = _train_setup(tmp_path)
    pub_url, pub_srv = publisher_node
    srv_url, srv = serving_node
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])
        p.wait()
        export_dir = str(tmp_path / "export")
        export_standalone(state, model, export_dir, model_sign="z")
        from openembedding_tpu.sync import SyncPublisher
        pub_srv.publishers["z"] = SyncPublisher(root)
        srv.manager.load_model("z", export_dir)
        for b in batches[1:3]:  # deltas at 2, 3
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()

    sub = SyncSubscriber(srv.manager, "z", pub_url, faults=_Truncate(2))
    srv.subscribers["z"] = sub  # registered on the node, like POST /sync
    assert sub.poll() == 0 and sub.state == "DEGRADED"

    with urllib.request.urlopen(f"{srv_url}/statusz") as resp:
        assert resp.status == 200
        text = resp.read().decode()
    assert "z: state=DEGRADED" in text
    assert "last_degraded_reason=" in text
    assert "torn payload" in text  # the actual apply-failure reason
    status, st, _ = _req(f"{srv_url}/models/z:syncstate")
    assert status == 200 and st["state"] == "DEGRADED"
    assert "torn payload" in st["last_degraded_reason"]
    # the DEGRADED->... transition + rollback landed in the flight recorder
    status, tz, _ = _req(f"{srv_url}/tracez")
    assert status == 200
    evs = [e for e in tz["events"] if e["group"] == "sync"]
    assert any(e["name"] == "rollback" for e in evs)
    assert any(e["name"] == "state" and e["attrs"].get("to") == "DEGRADED"
               for e in evs)

    sub.faults = None  # fault clears; the reason is kept for the post-mortem
    assert sub.poll() == 2 and sub.state == "IDLE"
    status, st, _ = _req(f"{srv_url}/models/z:syncstate")
    assert st["last_error"] is None
    assert "torn payload" in st["last_degraded_reason"]


def test_sync_behind_feed_retention_degrades(tmp_path, publisher_node):
    """A subscriber whose version fell behind the feed's base (its deltas
    GC'd under retention) cannot catch up incrementally: DEGRADED with the
    documented reload message, old servable untouched."""
    model, trainer, state, step, batches, root = _train_setup(tmp_path)
    pub_url, pub_srv = publisher_node
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=1) as p:  # fulls at 1 and 3
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])
        p.wait()
        export_dir = str(tmp_path / "export")
        export_standalone(state, model, export_dir, model_sign="b")
        for b in batches[1:3]:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
            p.wait()
        # deltas newer than the newest full so the head moves past the base
        p.full_every = 100
        for b in batches[3:5]:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
            p.wait()
    assert [s for s, _ in list_persists(root)][-1] == 3
    assert [s for s, _ in list_deltas(root)] == [4, 5]

    from openembedding_tpu.serving import ModelManager, ModelRegistry
    from openembedding_tpu.sync import SyncPublisher
    pub_srv.publishers["b"] = SyncPublisher(root)
    mgr = ModelManager(ModelRegistry(str(tmp_path / "reg_b")))
    mgr.load_model("b", export_dir)  # still at step 1 < base 4
    sub = SyncSubscriber(mgr, "b", pub_url)
    assert sub.poll() == 0
    assert sub.state == "DEGRADED"
    assert "reload" in sub.last_error
    assert mgr.find_model("b").step == 1


def test_sync_over_rest_admin_surface(tmp_path, publisher_node, serving_node):
    """The operator path: POST /publish on the trainer node, POST /sync on
    the serving node, progress visible via :syncstate — no Python API use."""
    model, trainer, state, step, batches, root = _train_setup(tmp_path)
    pub_url, pub_srv = publisher_node
    srv_url, srv = serving_node
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])
        p.wait()
        export_dir = str(tmp_path / "export")
        export_standalone(state, model, export_dir, model_sign="r")
        for b in batches[1:4]:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()

    status, out, _ = _req(f"{pub_url}/models/r/publish", "POST",
                          {"persist_root": root})
    assert status == 200 and out["head_step"] == 4
    status, _, _ = _req(f"{srv_url}/models/r", "POST",
                        {"model_uri": export_dir})
    assert status == 200
    status, out, _ = _req(f"{srv_url}/models/r/sync", "POST",
                          {"feed": pub_url, "interval_s": 0.05})
    assert status == 200 and out["state"] in ("IDLE", "FETCHING", "APPLYING")
    import time
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        status, st, _ = _req(f"{srv_url}/models/r:syncstate")
        assert status == 200
        if st["version"] == 4:
            break
        time.sleep(0.05)
    assert st["version"] == 4 and st["applied"] == 3, st
    # bad requests on the admin surface
    assert _req(f"{pub_url}/models/x/publish", "POST", {})[0] == 400
    assert _req(f"{pub_url}/models/x/publish", "POST",
                {"persist_root": "/nonexistent-dir"})[0] == 400
    assert _req(f"{srv_url}/models/x:syncstate")[0] == 404
    # DELETE stops the subscriber with the model
    status, _, _ = _req(f"{srv_url}/models/r", "DELETE")
    assert status == 200
    assert "r" not in srv.subscribers


def test_manager_swap_is_conditional(tmp_path):
    from openembedding_tpu.serving import ModelManager, ModelRegistry
    model, trainer, state, step, batches, _ = _train_setup(tmp_path)
    export_dir = str(tmp_path / "export")
    export_standalone(state, model, export_dir, model_sign="s")
    mgr = ModelManager(ModelRegistry(str(tmp_path / "reg")))
    with pytest.raises(KeyError):
        mgr.swap("s", object())  # not loaded -> refuses
    mgr.load_model("s", export_dir)
    cur = mgr.find_model("s")
    other = StandaloneModel.load(export_dir)
    with pytest.raises(RuntimeError, match="reloaded concurrently"):
        mgr.swap("s", other, expected=other)  # cache holds `cur`, not `other`
    mgr.swap("s", other, expected=cur)
    assert mgr.find_model("s") is other


def test_sharded_servable_apply_update_parity(tmp_path):
    """ShardedModel.apply_update: delta rows land in their owning shards
    (array scatter + per-shard hash probe), bit-equal to the live mesh
    state's rows, and the OLD servable still answers (RCU, no donation)."""
    from openembedding_tpu.parallel import MeshTrainer, make_mesh
    from openembedding_tpu.parallel.serving import ShardedModel
    from openembedding_tpu.persist import _load_delta_table

    mesh = make_mesh()
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(8,))
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=mesh,
                          seed=3)
    batches = list(synthetic_criteo(16, id_space=VOCAB, steps=4, seed=5))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    root = str(tmp_path / "persist")
    with IncrementalPersister(trainer, model, root, window=2,
                              policy=PersistPolicy(every_steps=1),
                              full_every=100) as p:
        state, _ = step(state, batches[0])
        p.maybe_persist(state, batch=batches[0])
        p.wait()
        ck = str(tmp_path / "ck")
        trainer.save(state, ck)
        for b in batches[1:3]:
            state, _ = step(state, b)
            p.maybe_persist(state, batch=b)
        p.wait()

    sm = ShardedModel.load(ck)
    assert sm.step == 1
    old = sm
    old_rows = np.asarray(old.lookup("categorical",
                                     np.arange(32, dtype=np.int64)))
    for dstep, dpath in list_deltas(root):
        with open(os.path.join(dpath, "meta.json")) as f:
            meta = json.load(f)
        tables = {}
        for name in meta["tables"]:
            ids, w, _slots = _load_delta_table(dpath, name)
            tables[name] = (ids, w)
        with np.load(os.path.join(dpath, "dense.npz")) as z:
            dense = {k[len("params/"):]: z[k] for k in z.files
                     if k.startswith("params/")}
        sm = sm.apply_update(tables, dense, step=meta["step"],
                             model_version=meta["model_version"])
        assert sm.step == dstep

    ids = np.unique(np.concatenate(
        [np.asarray(b["sparse"]["categorical"]).reshape(-1)
         for b in batches[:3]]))
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from openembedding_tpu.parallel.sharded import sharded_lookup
    spec = model.specs["categorical"]
    pull = jax.jit(jax.shard_map(
        partial(sharded_lookup, spec, axis=trainer.axis), mesh=trainer.mesh,
        in_specs=(trainer._table_pspec(spec), P()), out_specs=P(),
        check_vma=False))
    import jax.numpy as jnp
    np.testing.assert_array_equal(
        np.asarray(sm.lookup("categorical", ids)),
        np.asarray(pull(state.tables["categorical"], jnp.asarray(ids))))
    # RCU: the old servable was not donated away mid-apply
    np.testing.assert_array_equal(
        np.asarray(old.lookup("categorical", np.arange(32, dtype=np.int64))),
        old_rows)


def test_restore_from_peer_crash_safe(tmp_path, publisher_node, monkeypatch):
    """A restore that dies mid-page leaves NOTHING at dest (no half-written
    export a later create_model would load); a complete restore lands
    atomically and loads."""
    from openembedding_tpu.serving import restore_from_peer

    model, trainer, state, step, batches, _ = _train_setup(tmp_path)
    pub_url, pub_srv = publisher_node
    export_dir = str(tmp_path / "export")
    export_standalone(state, model, export_dir, model_sign="pr")
    pub_srv.manager.load_model("pr", export_dir)

    dest = str(tmp_path / "restored")
    # simulate the peer dying MID-PAGE: the third request (a :rows page, after
    # the model entry + manifest succeeded and pages started landing in the
    # staging dir) breaks the connection
    real_urlopen = urllib.request.urlopen
    calls = {"n": 0}

    def flaky(url, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 3:
            raise ConnectionError("peer died mid-page")
        return real_urlopen(url, *a, **kw)

    monkeypatch.setattr(urllib.request, "urlopen", flaky)
    with pytest.raises(ConnectionError):
        restore_from_peer(pub_url, "pr", dest)
    monkeypatch.undo()
    assert calls["n"] >= 3  # it really was mid-restore, not a pre-flight fail
    assert not os.path.exists(dest)
    assert not any(f.startswith("restored.tmp-")
                   for f in os.listdir(str(tmp_path)))

    out = restore_from_peer(pub_url, "pr", dest)
    assert out == dest
    sm = StandaloneModel.load(dest)
    np.testing.assert_array_equal(
        np.asarray(sm.lookup("categorical", np.arange(16, dtype=np.int64))),
        np.asarray(StandaloneModel.load(export_dir).lookup(
            "categorical", np.arange(16, dtype=np.int64))))
    # a restore over an EXISTING complete export replaces it atomically
    out2 = restore_from_peer(pub_url, "pr", dest)
    assert out2 == dest and os.path.exists(os.path.join(dest, "model_meta"))


def test_sync_soak_short(tmp_path):
    """The soak harness (tools/sync_soak.py) in its tier-1 configuration:
    trainer thread + subscriber-backed serving node, bounded version lag,
    zero failed predicts across the swaps."""
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "sync_soak", os.path.join(repo, "tools", "sync_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    report = soak.run(steps=6, persist_every=2, interval_s=0.05,
                      workdir=str(tmp_path / "soak"), predict_threads=2)
    assert report["failed_predicts"] == 0
    assert report["swaps"] >= 2
    assert report["final_lag_steps"] == 0
    assert report["predicts"] > 0


def test_sync_weave_short():
    """The soak's deterministic-interleaving variant (sync_soak --weave):
    the same actors explored under tools/oeweave — every schedule must hold
    the no-torn-status / no-lost-wakeup / clean-shutdown invariants. Short
    budget here; `make weave` runs the full one."""
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "sync_soak", os.path.join(repo, "tools", "sync_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    report = soak.run_weave(schedules=4, sweep=8, quiet=True)
    assert report["failures"] == 0
    per = report["scenarios"]
    assert set(per) == set(soak.WEAVE_SCENARIOS)
    assert all(v["explored"] >= 8 for v in per.values()), per
    assert report["schedules_explored"] >= 8 * len(per)
