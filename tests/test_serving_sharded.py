"""Sharded serving: pulls/predicts answered straight from a (sharded)
checkpoint on a serving mesh, the model NEVER materialized whole — the
reference's TF-Serving-reads-the-sharded-PS path (`exb_ops.cpp:261-276`,
`EmbeddingPullOperator.cpp:50-58`); REST `shard_num` now selects it."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.data import synthetic_criteo
from openembedding_tpu.models import make_deepfm
from openembedding_tpu.model import Trainer
from openembedding_tpu.parallel import MeshTrainer, make_mesh
from openembedding_tpu.parallel.serving import ShardedModel
from openembedding_tpu.serving import make_server

VOCAB = 1 << 10


@pytest.fixture(scope="module")
def mesh_trained():
    mesh = make_mesh()
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,))
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=mesh,
                          seed=3)
    batches = list(synthetic_criteo(32, id_space=VOCAB, steps=3, seed=5))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    for b in batches:
        state, _ = step(state, b)
    return model, trainer, state, batches[0]


def _assert_never_materialized(arr, num_shards):
    """Every device holds exactly rows/num_shards — nothing is replicated."""
    assert len(arr.sharding.device_set) == num_shards
    for s in arr.addressable_shards:
        assert s.data.shape[0] == arr.shape[0] // num_shards


def test_sharded_model_from_sharded_checkpoint(mesh_trained, tmp_path):
    model, trainer, state, batch = mesh_trained
    path = str(tmp_path / "ck")
    trainer.save(state, path)

    sm = ShardedModel.load(path)  # default mesh = all 8 devices
    _assert_never_materialized(sm.tables["categorical"].weights, 8)
    assert sm.tables["categorical"].slots == {}  # serving never loads slots

    # pull parity: global id order on disk, shard-major live layout
    from openembedding_tpu.parallel.sharded import deinterleave_rows
    ids = np.asarray([0, 1, 7, 513, VOCAB - 1], np.int64)
    want = np.asarray(deinterleave_rows(
        np.asarray(state.tables["categorical"].weights), 8, VOCAB))[ids]
    got = np.asarray(sm.lookup("categorical", ids))
    np.testing.assert_allclose(got, want, rtol=0, atol=0)

    # out-of-range ids -> zeros (read-only serving semantics)
    oob = np.asarray(sm.lookup("categorical", np.asarray([VOCAB + 5, -3])))
    assert (oob == 0).all()

    # predict parity vs the trainer's eval on the same batch
    ev = trainer.jit_eval_step(batch, state)(state, batch)
    logits = np.asarray(sm.predict(batch))
    np.testing.assert_allclose(logits.reshape(-1),
                               np.asarray(ev["logits"]).reshape(-1),
                               rtol=1e-4, atol=1e-5)


def test_sharded_model_from_single_checkpoint(tmp_path):
    """The single-file layout (Trainer.save) also serves sharded, at a
    different mesh size (1 -> 2 reshard on load)."""
    model = make_deepfm(vocabulary=VOCAB, dim=4, hidden=(16,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=3)
    batch = next(synthetic_criteo(32, id_space=VOCAB, steps=1, seed=5))
    state = trainer.init(batch)
    state, _ = trainer.jit_train_step()(state, batch)
    path = str(tmp_path / "ck1")
    trainer.save(state, path)

    mesh2 = make_mesh(jax.devices()[:2])
    sm = ShardedModel.load(path, mesh=mesh2)
    _assert_never_materialized(sm.tables["categorical"].weights, 2)
    ids = np.asarray([0, 3, 999], np.int64)
    want = np.asarray(state.tables["categorical"].weights)[ids]  # S=1: id order
    np.testing.assert_allclose(np.asarray(sm.lookup("categorical", ids)),
                               want, rtol=0, atol=0)


def test_sharded_model_hashed_variable(tmp_path):
    """Hash tables re-insert into the serving mesh's shards; absent -> zeros."""
    mesh = make_mesh()
    model = make_deepfm(vocabulary=-1, dim=4, hidden=(16,), hashed=True,
                        capacity=2048)
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=mesh)
    batches = list(synthetic_criteo(32, id_space=1 << 40, steps=2, seed=9))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step(batches[0], state)
    for b in batches:
        state, _ = step(state, b)
    path = str(tmp_path / "ckh")
    trainer.save(state, path)

    sm = ShardedModel.load(path, mesh=mesh)
    ids = np.unique(batches[0]["sparse"]["categorical"].reshape(-1))[:32]
    # oracle: read the same ids through the trainer's sharded read-only pull
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from openembedding_tpu.parallel.sharded import sharded_lookup
    spec = model.specs["categorical"]
    pull = jax.jit(jax.shard_map(
        partial(sharded_lookup, spec, axis=trainer.axis),
        mesh=mesh, in_specs=(trainer._table_pspec(spec), P()),
        out_specs=P(), check_vma=False))
    want = np.asarray(pull(state.tables["categorical"], jnp.asarray(ids)))
    np.testing.assert_allclose(np.asarray(sm.lookup("categorical", ids)),
                               want, rtol=0, atol=0)
    absent = np.asarray(sm.lookup("categorical", np.asarray([12345])))
    assert (absent == 0).all()


def test_sharded_model_serves_host_cached_checkpoint(tmp_path):
    """A host-cached (offloaded) model's store holds far MORE rows than its
    HBM cache capacity; the serving table must be sized from the checkpoint's
    id count, not the cache capacity — every trained row must serve."""
    import dataclasses
    from openembedding_tpu.model import EmbeddingModel

    # cache holds ONE batch's uniques (~723 < 0.6 * 2048, no overflow warning)
    # while the 6-batch stream's cumulative uniques far exceed it — the store,
    # not the cache, is the authoritative row set
    base = make_deepfm(vocabulary=-1, dim=4, hidden=(16,), hashed=True,
                       capacity=2048)
    spec = dataclasses.replace(base.specs["categorical"],
                               storage="host_cached")
    model = EmbeddingModel(base.module, [], loss_fn=base.loss_fn,
                           config=base.config)
    model.specs = {"categorical": spec}
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batches = list(synthetic_criteo(32, id_space=1 << 40, steps=6, seed=2))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error", RuntimeWarning)  # no capacity warnings
        for b in batches:
            state = trainer.offload_prepare(state, b)
            state, _ = step(state, b)
    ot = trainer.offload["categorical"]
    ot.adopt(state.tables["categorical"])
    ot.sync_to_store()
    assert ot.total_overflow == 0  # every trained row reached the store
    assert ot.store.ids.size > ot.capacity  # the store really exceeds the cache

    path = str(tmp_path / "ck_off")
    trainer.save(state, path)
    sm = ShardedModel.load(path, mesh=make_mesh(jax.devices()[:4]))
    # the serving table was sized from the store, not the 64-row cache
    assert sm.tables["categorical"].keys.shape[0] >= ot.store.ids.size
    ids = ot.store.ids[:200]
    want = ot.store.weights[:200]
    np.testing.assert_allclose(
        np.asarray(sm.lookup("categorical", ids)), want,
        rtol=1e-6, atol=1e-6)


@pytest.fixture()
def server(tmp_path):
    httpd = make_server(str(tmp_path / "registry"), port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", httpd
    httpd.shutdown()


def _req(url, method="GET", payload=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rest_sharded_serving(mesh_trained, tmp_path, server):
    """POST /models with shard_num=8 serves from the sharded checkpoint —
    shard_num is no longer a stored-but-ignored field."""
    model, trainer, state, batch = mesh_trained
    base, httpd = server
    path = str(tmp_path / "rest_ck")
    trainer.save(state, path)

    status, entry = _req(f"{base}/models", "POST",
                         {"model_sign": "big-0", "model_uri": path,
                          "replica_num": 1, "shard_num": 8})
    assert status == 200 and entry["status"] == "NORMAL"
    assert isinstance(httpd.manager._cache["big-0"], ShardedModel)
    _assert_never_materialized(
        httpd.manager._cache["big-0"].tables["categorical"].weights, 8)

    ids = [0, 1, 7, 513]
    status, out = _req(f"{base}/models/big-0/pull", "POST",
                       {"variable": "categorical", "ids": ids})
    assert status == 200
    from openembedding_tpu.parallel.sharded import deinterleave_rows
    want = np.asarray(deinterleave_rows(
        np.asarray(state.tables["categorical"].weights), 8, VOCAB))[ids]
    np.testing.assert_allclose(np.asarray(out["weights"], np.float32), want,
                               rtol=1e-6, atol=1e-6)

    status, out = _req(f"{base}/models/big-0/predict", "POST",
                       {"sparse": {"categorical":
                                   batch["sparse"]["categorical"].tolist()},
                        "dense": np.asarray(batch["dense"]).tolist()})
    assert status == 200
    ev = trainer.jit_eval_step(batch, state)(state, batch)
    np.testing.assert_allclose(np.asarray(out["logits"]).reshape(-1),
                               np.asarray(ev["logits"]).reshape(-1),
                               rtol=1e-3, atol=1e-4)

    # a missing sparse feature is the CALLER's error: 400, never 404
    status, out = _req(f"{base}/models/big-0/predict", "POST",
                       {"sparse": {}})
    assert status == 400 and "categorical" in out["error"]

    # a shard_num beyond this node's devices must be refused and recorded
    status, out = _req(f"{base}/models", "POST",
                       {"model_sign": "toobig-0", "model_uri": path,
                        "shard_num": 64})
    assert status == 500
    status, entry = _req(f"{base}/models/toobig-0")
    assert status == 200 and entry["status"] == "ERROR"


def test_request_padding_bounds_compile_cache(mesh_trained, tmp_path):
    """Varying request sizes reuse O(log n) compiled programs (bucketed
    padding) and answers stay correct at every size — the batching/padding
    policy the reference delegates to TF-Serving's batcher."""
    model, trainer, state, batch = mesh_trained
    path = str(tmp_path / "ck_pad")
    trainer.save(state, path)
    sm = ShardedModel.load(path)

    from openembedding_tpu.parallel.sharded import deinterleave_rows
    table = np.asarray(deinterleave_rows(
        np.asarray(state.tables["categorical"].weights), 8, VOCAB))
    for n in (1, 2, 3, 5, 7, 8, 11, 13):
        ids = np.arange(n, dtype=np.int64)
        got = np.asarray(sm.lookup("categorical", ids))
        np.testing.assert_allclose(got, table[:n], rtol=0, atol=0)
    # every size <= 8 shares the 8-bucket, 11/13 share the 16-bucket: the
    # jitted pull compiled at most TWO shapes for eight request sizes
    assert sm._lookup_fns["categorical"]._cache_size() <= 2

    # ragged requests are rejected, never silently padded (wrong logits)
    from openembedding_tpu.export import RaggedBatchError
    bad = {"sparse": {"categorical": batch["sparse"]["categorical"][:6]},
           "dense": np.asarray(batch["dense"])[:3]}
    with pytest.raises(RaggedBatchError, match="ragged"):
        sm.predict(bad)

    logits = {}
    for n in (1, 3, 4, 6):
        b = {"sparse": {"categorical": batch["sparse"]["categorical"][:n]},
             "dense": np.asarray(batch["dense"])[:n]}
        logits[n] = np.asarray(sm.predict(b)).reshape(-1)
        assert logits[n].shape[0] == n
    np.testing.assert_allclose(logits[3], logits[6][:3], rtol=1e-5, atol=1e-6)


def test_restore_from_sharded_peer(mesh_trained, tmp_path, server):
    """`restore_from_peer` against a SHARDED serving peer: the rows stream out
    through the read-only sharded pull (never materialized on the peer), and
    the restored standalone export answers identically — the reference's
    replica-iteration restore with a sharded source."""
    from openembedding_tpu.export import StandaloneModel
    from openembedding_tpu.serving import restore_from_peer

    model, trainer, state, batch = mesh_trained
    base, httpd = server
    path = str(tmp_path / "peer_ck")
    trainer.save(state, path)
    status, entry = _req(f"{base}/models", "POST",
                         {"model_sign": "shpeer-0", "model_uri": path,
                          "shard_num": 8})
    assert status == 200 and entry["status"] == "NORMAL"

    # page size < vocab forces multi-page row iteration on the sharded source
    dest = restore_from_peer(base, "shpeer-0", str(tmp_path / "restored"),
                             page=300)
    restored = StandaloneModel.load(dest)

    ids = np.asarray([0, 1, 7, 513, VOCAB - 1])
    status, want = _req(f"{base}/models/shpeer-0/pull", "POST",
                        {"variable": "categorical", "ids": ids.tolist()})
    assert status == 200
    got = np.asarray(restored.lookup("categorical", ids))
    np.testing.assert_allclose(got, np.asarray(want["weights"], np.float32),
                               rtol=1e-6, atol=1e-6)

    # predict parity: sharded peer vs restored standalone
    body = {"sparse": {"categorical":
                       batch["sparse"]["categorical"].tolist()},
            "dense": np.asarray(batch["dense"]).tolist()}
    status, peer_out = _req(f"{base}/models/shpeer-0/predict", "POST", body)
    assert status == 200
    mine = np.asarray(restored.predict(
        {"sparse": batch["sparse"], "dense": batch["dense"]})).reshape(-1)
    np.testing.assert_allclose(mine, np.asarray(peer_out["logits"]).reshape(-1),
                               rtol=1e-3, atol=1e-4)


def test_export_rows_pair_layout_hash(tmp_path, server):
    """The live-replica export surface over a 63-bit split-pair hash table:
    resident-id enumeration from (capacity, 2) uint32 keys, paged rows, and a
    restored export answering identically (int64 ids in, pair probe inside)."""
    from openembedding_tpu.export import StandaloneModel
    from openembedding_tpu.serving import restore_from_peer

    mesh = make_mesh()
    with jax.enable_x64(False):  # pin the split-pair key layout
        model = make_deepfm(vocabulary=-1, dim=4, hidden=(16,), hashed=True,
                            capacity=2048)
        trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.05),
                              mesh=mesh)
        batches = list(synthetic_criteo(32, id_space=1 << 40, steps=2, seed=9,
                                        ids_dtype="pair"))
        state = trainer.init(batches[0])
        assert state.tables["categorical"].keys.ndim == 2  # pair layout
        step = trainer.jit_train_step(batches[0], state)
        for b in batches:
            state, _ = step(state, b)
        path = str(tmp_path / "ck_pair")
        trainer.save(state, path)

    base, httpd = server
    status, entry = _req(f"{base}/models", "POST",
                         {"model_sign": "pair-0", "model_uri": path,
                          "shard_num": 8})
    assert status == 200 and entry["status"] == "NORMAL"
    peer_model = httpd.manager._cache["pair-0"]
    man = peer_model.export_manifest()
    (v,) = [x for x in man["variables"] if x["storage_name"] == "categorical"]
    assert v["kind"] == "hash" and v["rows"] > 0

    dest = restore_from_peer(base, "pair-0", str(tmp_path / "restored_pair"),
                             page=7)  # multi-page over the resident ids
    restored = StandaloneModel.load(dest)
    from openembedding_tpu.ops.id64 import np_join_ids
    probe = np_join_ids(batches[0]["sparse"]["categorical"].reshape(-1, 2))[:16]
    want = np.asarray(peer_model.lookup("categorical",
                                        probe.astype(np.int64)))
    got = np.asarray(restored.lookup("categorical", probe.astype(np.int64)))
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_sharded_model_serves_combiner_checkpoint(tmp_path):
    """ShardedModel.predict pools multivalent (combiner) features straight
    from a sharded checkpoint: ragged-padded requests match the trainer's
    eval, and a WIDER pad of the same request changes nothing (serve_rows'
    host-ids mask)."""
    from openembedding_tpu.models import make_two_tower

    mesh = make_mesh()
    model = make_two_tower(256, 128, dim=4, tower=(8,), combiner="mean",
                           compute_dtype=jnp.float32)
    trainer = MeshTrainer(model, embed.Adagrad(learning_rate=0.1), mesh=mesh,
                          seed=2)
    batch = {"sparse": {"user": jnp.asarray([[1, 2, -1], [3, -1, -1]] * 4),
                        "item": jnp.asarray([[5, -1], [6, 7]] * 4)},
             "dense": None, "label": None}
    state = trainer.init(batch)
    state, _ = trainer.jit_train_step(batch, state)(state, batch)
    path = str(tmp_path / "comb_ck")
    trainer.save(state, path)

    sm = ShardedModel.load(path)
    # oracle: the standalone export of the SAME state (the mesh trainer's own
    # eval scores per-SHARD in-batch matrices — local negatives under DP —
    # so serving, which sees the whole request, matches the standalone view)
    from openembedding_tpu.export import StandaloneModel, export_standalone
    spath = str(tmp_path / "comb_standalone")
    export_standalone(state, model, spath, num_shards=trainer.num_shards)
    req = {"sparse": {k: np.asarray(v) for k, v in batch["sparse"].items()}}
    want = np.asarray(StandaloneModel.load(spath, model=model).predict(req))
    got = np.asarray(sm.predict(req))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # widening the pad (3 -> 5 columns of -1) must not move the logits
    wider = {"sparse": {
        "user": np.concatenate(
            [np.asarray(batch["sparse"]["user"]),
             np.full((8, 2), -1, np.int64)], axis=1),
        "item": np.asarray(batch["sparse"]["item"])}}
    got_w = np.asarray(sm.predict(wider))
    np.testing.assert_allclose(got_w, got, rtol=1e-5, atol=1e-6)
