"""Fused multi-table exchange + quantized wire payloads (`ops/wire.py`,
`parallel/sharded.grouped_*`).

Covers the round-6 tentpole contracts:
- 3 all_to_alls per DIM-GROUP (not per table), pinned at the HLO level for a
  3-table / 2-group model (6 fused vs 9 unfused);
- the fused exchange with fp32 wire is BIT-identical to the per-table
  protocol (grouping only shares the wire, never the math);
- bf16 (default) / int8 (opt-in) wire: pull rows and pushed grads round-trip
  within format tolerance, duplicate-count lanes and overflow counters stay
  EXACT, table storage stays full-precision fp32;
- the static wire-cost model: bf16 moves >= 1.8x fewer exchange bytes/step
  than fp32 (the tools/wire_microbench.py acceptance number).

The suite-wide default wire is pinned to fp32 in tests/conftest.py (parity
tests elsewhere assert exact agreement); every lossy-format test here passes
`wire=` explicitly.
"""

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.model import EmbeddingModel
from openembedding_tpu.ops import wire
from openembedding_tpu.parallel import MeshTrainer, make_mesh

S = 8
B = 4 * S
FMTS = ("fp32", "bf16", "int8")


# ---------------------------------------------------------------------------
# wire codec units
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt", FMTS)
def test_counts_roundtrip_exact(fmt):
    """Duplicate counts must survive the wire bit-exactly in EVERY format —
    they divide/weight optimizer updates (1 fp32 / 2 bf16 / 4 int8 lanes)."""
    counts = jnp.asarray(
        np.array([0, 1, 2, 3, 127, 128, 255, 65536, (1 << 30) + 17, 4096],
                 np.int32))
    lanes = wire.counts_to_lanes(counts, fmt)
    assert lanes.shape == (10, wire.count_lanes(fmt))
    # lanes travel in the CARRIER dtype (bf16 ships as uint16 so XLA:CPU's
    # bf16->f32 float normalization can't widen the compiled collective)
    assert lanes.dtype == wire.wire_carrier_dtype(fmt)
    np.testing.assert_array_equal(np.asarray(wire.lanes_to_counts(lanes)),
                                  np.asarray(counts))


@pytest.mark.parametrize("fmt", FMTS)
def test_rows_roundtrip_within_format_tolerance(fmt):
    rng = np.random.default_rng(0)
    rows = rng.standard_normal((64, 16)).astype(np.float32) * 3.0
    rows[5] = 0.0  # all-zero row: must decode to exact zeros (int8 scale 0)
    enc = wire.encode_rows(jnp.asarray(rows), fmt)
    assert enc.shape[1] == wire.rows_wire_width(16, fmt)
    dec = np.asarray(wire.decode_rows(enc, 16, fmt))
    if fmt == "fp32":
        np.testing.assert_array_equal(dec, rows)
    elif fmt == "bf16":
        np.testing.assert_allclose(dec, rows, rtol=2 ** -8, atol=1e-7)
    else:  # int8: per-row max-abs scaling -> error <= scale/2 per element
        step = np.abs(rows).max(axis=1, keepdims=True) / 127.0
        assert np.all(np.abs(dec - rows) <= step * 0.5 + 1e-7)
    np.testing.assert_array_equal(dec[5], 0.0)


@pytest.mark.parametrize("fmt", FMTS)
def test_grads_payload_and_empty_slots(fmt):
    """encode_grads folds grads + exact counts into one payload row; a ZERO
    payload row (what empty bucket slots carry) decodes to grad 0, count 0."""
    rng = np.random.default_rng(1)
    g = rng.standard_normal((32, 8)).astype(np.float32)
    counts = jnp.asarray(rng.integers(0, 1 << 20, 32).astype(np.int32))
    enc = wire.encode_grads(jnp.asarray(g), counts, fmt)
    assert enc.shape[1] == wire.grads_wire_width(8, fmt)
    dec_g, dec_c = wire.decode_grads(enc, 8, fmt)
    np.testing.assert_array_equal(np.asarray(dec_c), np.asarray(counts))
    tol = {"fp32": 0.0, "bf16": 2 ** -8, "int8": 1 / 64}[fmt]
    np.testing.assert_allclose(np.asarray(dec_g), g, rtol=tol,
                               atol=tol * np.abs(g).max() + 1e-7)
    zero_g, zero_c = wire.decode_grads(jnp.zeros_like(enc), 8, fmt)
    np.testing.assert_array_equal(np.asarray(zero_g), 0.0)
    np.testing.assert_array_equal(np.asarray(zero_c), 0)


def test_concat_split_buckets_mixed_int_widths():
    """int32 + int64 bucket arrays fuse onto an int64 wire and narrow back;
    sentinels (-1) survive both directions."""
    from openembedding_tpu.ops.dedup import (concat_owner_buckets,
                                             split_owner_buckets)
    a = jnp.asarray(np.array([[1, -1, 5], [7, 3, -1]], np.int32))
    b = jnp.asarray(np.array([[1 << 40, -1], [-1, (1 << 33) + 9]], np.int64))
    fused = concat_owner_buckets([a, b])
    assert fused.dtype == jnp.int64 and fused.shape == (2, 5)
    back = split_owner_buckets(fused, [(3, False, a.dtype),
                                       (2, False, b.dtype)])
    np.testing.assert_array_equal(np.asarray(back[0]), np.asarray(a))
    assert back[0].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(back[1]), np.asarray(b))


def test_concat_split_buckets_pair_widening():
    """A split-pair table beside a single-lane array table widens the group
    onto the pair wire; the array table's segment narrows back with its
    sentinels intact (`ops/id64` machinery)."""
    from openembedding_tpu.ops.dedup import (concat_owner_buckets,
                                             split_owner_buckets)
    from openembedding_tpu.ops.id64 import np_split_ids
    ids64 = np.array([[(1 << 45) + 3, -1], [-1, (1 << 62) - 5]], np.int64)
    pair = jnp.asarray(np_split_ids(ids64))                  # (2, 2, 2)
    flat = jnp.asarray(np.array([[4, -1, 0], [-1, 2, 7]], np.int32))
    fused = concat_owner_buckets([pair, flat])
    assert fused.ndim == 3 and fused.shape == (2, 5, 2)
    back = split_owner_buckets(fused, [(2, True, pair.dtype),
                                       (3, False, flat.dtype)])
    np.testing.assert_array_equal(np.asarray(back[0]), np.asarray(pair))
    np.testing.assert_array_equal(np.asarray(back[1]), np.asarray(flat))


# ---------------------------------------------------------------------------
# the 3-table / 2-dim-group model the fused-exchange pins train
# ---------------------------------------------------------------------------


class _ThreeTower(nn.Module):
    """Reads two dim-8 tables + one dim-1 table -> logits (B,)."""

    @nn.compact
    def __call__(self, embedded, dense):
        bias = self.param("bias", nn.initializers.zeros, (1,), jnp.float32)
        out = (jnp.sum(embedded["a"].astype(jnp.float32), axis=(1, 2))
               + jnp.sum(embedded["b"].astype(jnp.float32), axis=(1, 2))
               + jnp.sum(embedded["w"][..., 0].astype(jnp.float32), axis=1))
        return out + bias[0]


def _three_table_model(vocab=64):
    """3 PS tables in 2 dim-groups: dim-8 {a (array), b (hash)} + dim-1 {w}.
    The hash table keys in int64 under the suite's x64 config, so the fused
    id wire exercises the mixed int32/int64 promotion path too."""
    embs = [
        embed.Embedding(vocab, 8, name="a",
                        embeddings_initializer=embed.Constant(0.05)),
        embed.Embedding(-1, 8, name="b", capacity=4096,
                        embeddings_initializer=embed.Constant(0.02)),
        embed.Embedding(vocab, 1, name="w",
                        embeddings_initializer=embed.Constant(0.0)),
    ]
    return EmbeddingModel(_ThreeTower(), embs)


def _batch(rng, vocab=64, dupes=True, hash_space=1 << 40,
           hash_dtype=np.int64):
    a = rng.integers(0, vocab, (B, 4)).astype(np.int32)
    b = rng.integers(0, hash_space, (B, 3)).astype(hash_dtype)
    if dupes:  # duplicate-heavy streams: the count lanes must carry > 1
        a[:, 0] = 7
        b[:, 0] = hash_space - 13
    w = rng.integers(0, vocab, (B, 4)).astype(np.int32)
    return {"sparse": {"a": a, "b": b, "w": w},
            "label": rng.integers(0, 2, (B,)).astype(np.float32)}


def _train(trainer, batches, state=None):
    if state is None:
        state = trainer.init(batches[0])
    if isinstance(trainer, MeshTrainer):
        step = trainer.jit_train_step(batches[0], state)
    else:
        step = trainer.jit_train_step()
    losses = []
    for b in batches:
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    return state, losses


def _probe_tables(trainer, state, batches, vocab=64):
    """Deterministic table reads for comparison across trainers: the array
    tables read fully, the hash table reads every id the batches trained."""
    from openembedding_tpu.embedding import lookup as single_lookup
    from openembedding_tpu.parallel.sharded import sharded_lookup
    from jax.sharding import PartitionSpec as P
    from functools import partial
    out = {}
    probes = {"a": np.arange(vocab, dtype=np.int32),
              "b": np.unique(np.concatenate(
                  [b["sparse"]["b"].reshape(-1) for b in batches])),
              "w": np.arange(vocab, dtype=np.int32)}
    for name, probe in probes.items():
        spec = trainer.model.specs[name]
        if isinstance(trainer, MeshTrainer):
            pull = jax.jit(jax.shard_map(
                partial(sharded_lookup, spec, axis=trainer.axis),
                mesh=trainer.mesh,
                in_specs=(trainer._table_pspec(spec), P()),
                out_specs=P(), check_vma=False))
            out[name] = np.asarray(pull(state.tables[name],
                                        jnp.asarray(probe)))
        else:
            out[name] = np.asarray(single_lookup(
                spec, state.tables[name], jnp.asarray(probe)))
    return out


# ---------------------------------------------------------------------------
# fused-exchange pins
# ---------------------------------------------------------------------------


def test_fused_step_compiles_three_all_to_alls_per_dim_group():
    """THE acceptance pin: a 3-table model in 2 dim-groups compiles to 6
    all_to_alls per train step (3 per GROUP); the pre-fusion per-table
    protocol (group_exchange=False) compiles the same model to 9."""
    import re

    def count_a2a(group_exchange):
        rng = np.random.default_rng(0)
        tr = MeshTrainer(_three_table_model(),
                         embed.Adagrad(learning_rate=0.05), mesh=make_mesh(),
                         group_exchange=group_exchange)
        b = _batch(rng)
        state = tr.init(b)
        step = tr.jit_train_step(b, state)
        txt = step.lower(state, b).compile().as_text()
        return len(re.findall(r" all-to-all(?:-start)?\(", txt))

    assert count_a2a(True) == 6, "fused: expected 3 a2a per dim-group"
    assert count_a2a(False) == 9, "unfused: expected 3 a2a per table"


def test_fused_fp32_bitexact_vs_per_table_protocol():
    """Grouping shares the WIRE, never the math: with fp32 wire the fused
    exchange must reproduce the per-table protocol bit for bit (same dedup,
    same bucket contents, same apply order)."""
    rng = np.random.default_rng(1)
    batches = [_batch(rng) for _ in range(3)]

    def run(group_exchange):
        tr = MeshTrainer(_three_table_model(),
                         embed.Adagrad(learning_rate=0.1), mesh=make_mesh(),
                         wire="fp32", group_exchange=group_exchange)
        state, losses = _train(tr, batches)
        return _probe_tables(tr, state, batches), losses

    fused, l_fused = run(True)
    per_table, l_per = run(False)
    np.testing.assert_array_equal(l_fused, l_per)
    for name in fused:
        np.testing.assert_array_equal(fused[name], per_table[name])


@pytest.mark.parametrize("fmt", ["bf16", "int8"])
def test_quantized_wire_parity_and_fp32_storage(fmt):
    """Lossy wire formats: trained tables stay within format tolerance of the
    fp32-wire run (pull rows AND pushed grads both cross the wire every
    step), storage dtype stays fp32, and the duplicate-heavy stream keeps
    count-dependent updates sane (mangled count lanes would be gross)."""
    rng = np.random.default_rng(2)
    batches = [_batch(rng) for _ in range(3)]

    def run(wire_fmt):
        tr = MeshTrainer(_three_table_model(),
                         embed.Adagrad(learning_rate=0.1), mesh=make_mesh(),
                         wire=wire_fmt)
        state, losses = _train(tr, batches)
        for ts in state.tables.values():
            assert ts.weights.dtype == jnp.float32  # storage never quantizes
        return _probe_tables(tr, state, batches), losses

    exact, l_exact = run("fp32")
    lossy, l_lossy = run(fmt)
    # pull rows + grads each round once per step; 3 steps of Adagrad compound
    tol = 0.02 if fmt == "bf16" else 0.06
    for name in exact:
        np.testing.assert_allclose(lossy[name], exact[name], rtol=tol,
                                   atol=tol)
    np.testing.assert_allclose(l_lossy, l_exact, rtol=tol)
    assert max(abs(np.asarray(v)).max() for v in lossy.values()) > 0


@pytest.mark.parametrize("fmt", ["bf16", "int8"])
def test_overflow_drop_paths_unchanged_by_wire(fmt):
    """Bounded buckets under capacity pressure: overflow counters are an
    ID-side property and must be IDENTICAL across wire formats; dropped ids
    still pull zeros / drop grads (training stays finite)."""
    rng = np.random.default_rng(3)
    batches = [_batch(rng) for _ in range(2)]

    def run(wire_fmt):
        tr = MeshTrainer(_three_table_model(),
                         embed.Adagrad(learning_rate=0.1), mesh=make_mesh(),
                         capacity_factor=0.25, wire=wire_fmt)
        state = tr.init(batches[0])
        step = tr.jit_train_step(batches[0], state)
        oflow = {}
        for b in batches:
            state, m = step(state, b)
            for k, v in m["stats"].items():
                if k.endswith("_overflow"):
                    oflow[k] = oflow.get(k, 0) + int(np.asarray(v))
            assert np.isfinite(float(m["loss"]))
        return oflow

    o_exact = run("fp32")
    o_lossy = run(fmt)
    # the duplicate-saturated streams overflow the 0.25-factor buckets
    assert sum(o_exact.values()) > 0
    assert o_lossy == o_exact


def test_wire_cost_model_and_gauges():
    """Static cost model: bf16 >= 1.8x fewer exchange bytes/step than fp32
    (the microbench acceptance bound), int8 beats bf16, fused <= unfused
    collectives; the trainer publishes the gauges at trace time."""
    from openembedding_tpu.utils import metrics as M

    tables = [{"dim": 16, "cap": 128, "pair": False, "id_itemsize": 4},
              {"dim": 16, "cap": 128, "pair": False, "id_itemsize": 8},
              {"dim": 1, "cap": 64, "pair": False, "id_itemsize": 4}]
    fp32 = wire.exchange_cost(tables, S, "fp32")
    bf16 = wire.exchange_cost(tables, S, "bf16")
    int8 = wire.exchange_cost(tables, S, "int8")
    assert fp32["collectives_per_step"] == 6  # 2 dim-groups
    assert wire.exchange_cost(tables, S, "fp32",
                              fused=False)["collectives_per_step"] == 9
    assert fp32["bytes_per_step"] / bf16["bytes_per_step"] >= 1.8
    assert int8["bytes_per_step"] < bf16["bytes_per_step"]

    rng = np.random.default_rng(4)
    tr = MeshTrainer(_three_table_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire="bf16")
    b = _batch(rng)
    state = tr.init(b)
    _train(tr, [b], state=state)
    assert tr.last_wire_cost is not None
    assert tr.last_wire_cost["collectives_per_step"] == 6
    vals = M.report()
    assert vals.get("exchange.collectives_per_step") == 6.0
    assert vals.get("exchange.wire_bytes_per_step", 0) > 0


def test_grouped_pair_wire_x64_off():
    """Under x64-off the hash table keys in the split-pair layout; grouped
    with an int32 array table the fused id wire widens to pairs. Parity vs
    the per-table protocol stays exact (fp32 wire)."""
    with jax.enable_x64(False):
        rng = np.random.default_rng(5)
        # int32 ids (< 2^31: nothing to truncate); adapt_batch_ids widens
        # them onto the pair key layout at the protocol entry
        batches = [_batch(rng, hash_space=1 << 20, hash_dtype=np.int32)
                   for _ in range(2)]

        def run(group_exchange):
            tr = MeshTrainer(_three_table_model(),
                             embed.Adagrad(learning_rate=0.1),
                             mesh=make_mesh(), wire="fp32",
                             group_exchange=group_exchange)
            state, losses = _train(tr, batches)
            assert state.tables["b"].keys.ndim == 2  # pair-keyed
            return losses

        np.testing.assert_array_equal(run(True), run(False))


# ---------------------------------------------------------------------------
# round 17: per-table wire (dim-groups split on (dim, fmt))
# ---------------------------------------------------------------------------


def test_mixed_wire_splits_dim_groups_and_pins_a2a_count():
    """Per-table wire: `wire={table: fmt}` resolves once at trace time and
    the fused exchange keys its groups on (dim, fmt) — {a: int8, *: fp32}
    splits the dim-8 {a, b} group in two (3 groups -> 9 a2as) with both s8
    and f32 payload lanes in the compiled HLO, while a format-uniform dict
    is an identity split that compiles the round-13 program unchanged
    (6 a2as, same bytes as the plain-string config)."""
    import re

    def compile_txt(wire_cfg):
        rng = np.random.default_rng(6)
        tr = MeshTrainer(_three_table_model(),
                         embed.Adagrad(learning_rate=0.05), mesh=make_mesh(),
                         wire=wire_cfg, group_exchange=True)
        b = _batch(rng)
        state = tr.init(b)
        step = tr.jit_train_step(b, state)
        return step.lower(state, b).compile().as_text()

    def a2a_count(txt):
        return len(re.findall(r" all-to-all(?:-start)?\(", txt))

    def a2a_dtypes(txt):
        # result types on the definition head (tuple results list each
        # tensor), same parse the oelint hlo-budget pass pins bytes with
        out = set()
        for line in txt.splitlines():
            m = re.search(r" all-to-all(?:-start)?\(", line)
            if m:
                out |= {d for d in re.findall(
                    r"(pred|bf16|f32|s8|u8|s16|u16|s32|u32|s64|u64)\[",
                    line[:m.start()])}
        return out

    mixed = compile_txt({"a": "int8", "*": "fp32"})
    assert a2a_count(mixed) == 9, "mixed formats: expected 3 a2a groups"
    assert {"s8", "f32"} <= a2a_dtypes(mixed)
    uniform = compile_txt({"*": "fp32"})
    baseline = compile_txt("fp32")
    assert a2a_count(uniform) == 6
    assert a2a_count(baseline) == 6
    assert a2a_dtypes(uniform) == a2a_dtypes(baseline)


def test_mixed_wire_counts_lanes_bit_exact_and_gauges_truthful():
    """Mixed formats split a dim-group's payload wire but never the id side:
    under {a: int8, *: fp32} every count-lane-derived stat (dedup counts,
    bucket fill, shard loads, overflow) is BIT-identical to the all-fp32
    run, and the fp32-wired tables move only through the second-order logit
    shift a's quantized rows cause (~1e-8), orders of magnitude below a's
    own quantization error. The per-table `exchange.wire_dtype{table=}`
    gauges report the mixed wire truthfully."""
    from openembedding_tpu.utils import metrics as M

    rng = np.random.default_rng(7)
    batches = [_batch(rng) for _ in range(3)]

    def run(wire_cfg):
        M._REGISTRY.clear()
        tr = MeshTrainer(_three_table_model(),
                         embed.Adagrad(learning_rate=0.1), mesh=make_mesh(),
                         wire=wire_cfg, group_exchange=True)
        state = tr.init(batches[0])
        step = tr.jit_train_step(batches[0], state)
        stats = []
        for b in batches:
            state, m = step(state, b)
            stats.append({k: np.asarray(v) for k, v in m["stats"].items()})
        return _probe_tables(tr, state, batches), stats, M.report()

    exact, st_exact, _ = run("fp32")
    mixed, st_mixed, rep = run({"a": "int8", "*": "fp32"})
    # id/count lanes: every stat the exchange derives from ids is bitwise
    # unchanged by the payload-format split
    for se, sm in zip(st_exact, st_mixed):
        assert sorted(se) == sorted(sm)
        for k in se:
            np.testing.assert_array_equal(se[k], sm[k], err_msg=k)
    # a rides int8 (s8 lanes pinned in the HLO test above) within format
    # tolerance; the fp32-wired tables see no quantizer at all — their
    # drift is only the second-order logit shift from a's quantized rows
    np.testing.assert_allclose(mixed["a"], exact["a"], rtol=0.06, atol=0.06)
    d_rest = max(np.abs(mixed["b"] - exact["b"]).max(),
                 np.abs(mixed["w"] - exact["w"]).max())
    assert d_rest < 1e-6, d_rest
    assert rep['exchange.wire_dtype{table="a"}'] == 1.0   # s8 itemsize
    assert rep['exchange.wire_dtype{table="b"}'] == 4.0   # f32 itemsize
    assert rep['exchange.wire_dtype{table="w"}'] == 4.0


def test_wire_dict_validation():
    """Unknown table names and bogus formats fail at construction, not at
    trace time three layers deep."""
    with pytest.raises(ValueError, match="unknown tables"):
        MeshTrainer(_three_table_model(), embed.Adagrad(learning_rate=0.1),
                    mesh=make_mesh(), wire={"nope": "int8"})
    with pytest.raises(ValueError):
        MeshTrainer(_three_table_model(), embed.Adagrad(learning_rate=0.1),
                    mesh=make_mesh(), wire={"a": "int7"})
