"""Model zoo + data pipeline tests (tiny shapes, single device + 8-dev mesh).

The reference's equivalent coverage is its example-scripts-as-tests sweep
(`build.sh test`: every model x settings smoke-trained; SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.data import (CriteoBatcher, hash_category,
                                    read_criteo_tsv, synthetic_criteo)
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import (make_dcn, make_deepfm, make_dlrm,
                                      make_lr, make_two_tower, make_wdl,
                                      make_xdeepfm)
from openembedding_tpu.parallel import MeshTrainer, make_mesh

VOCAB = 512


def _smoke_train(model, batch, steps=3):
    tr = Trainer(model, embed.Adagrad(learning_rate=0.05))
    state = tr.init(batch)
    step = tr.jit_train_step()
    losses = []
    for _ in range(steps):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    return losses


def _ctr_batch(B=32, F=26, dense=13, seed=0):
    b = next(synthetic_criteo(B, id_space=VOCAB, num_fields=F, dense_dim=dense,
                              steps=1, seed=seed))
    return b


@pytest.mark.parametrize("maker,kw", [
    (make_dcn, {"dim": 8, "num_cross": 2}),
    (make_lr, {}),
    (make_wdl, {"dim": 4, "hidden": (16, 8)}),
    (make_deepfm, {"dim": 4, "hidden": (16, 8)}),
    (make_xdeepfm, {"dim": 4, "hidden": (16,), "cin_layers": (8, 8)}),
    (make_dlrm, {"dim": 4, "bottom": (16,), "top": (16,)}),
])
def test_ctr_models_train(maker, kw):
    model = maker(VOCAB, **kw)
    _smoke_train(model, _ctr_batch())


def test_deepfm_learns_signal():
    """Loss must actually drop on the synthetic linear-model labels."""
    model = make_deepfm(VOCAB, dim=4, hidden=(32, 16),
                        compute_dtype=jnp.float32)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.1))
    it = synthetic_criteo(256, id_space=VOCAB, steps=30, seed=3)
    first = next(it)
    state = tr.init(first)
    step = tr.jit_train_step()
    losses = []
    for b in it:
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.01, losses


def test_deepfm_on_mesh_matches_single_device():
    """Starting from identical tables/params, the 8-device mesh step must equal
    the 1-device step (the SPMD program IS the parameter server — no drift).
    Init RNG streams differ between the two trainers, so the mesh state is seeded
    from the single-device one via the interleave relayout."""
    from jax.sharding import NamedSharding
    from openembedding_tpu.parallel import interleave_rows

    from openembedding_tpu.model import binary_logloss

    batch = _ctr_batch(B=64, seed=5)
    model = make_deepfm(VOCAB, dim=4, hidden=(16, 8),
                        compute_dtype=jnp.float32)
    # Mesh semantics are Horovod op=Sum parity: psum of per-shard mean-loss grads
    # == grads of 8 * global mean. Give the single-device model the same effective
    # loss so the comparison is exact.
    model.loss_fn = lambda lg, lb: 8.0 * binary_logloss(lg, lb)
    t1 = Trainer(model, embed.Adagrad(learning_rate=0.05))
    s1 = t1.init(batch)
    model2 = make_deepfm(VOCAB, dim=4, hidden=(16, 8),
                         compute_dtype=jnp.float32)
    t8 = MeshTrainer(model2, embed.Adagrad(learning_rate=0.05))
    s8 = t8.init(batch)

    # transplant the 1-device table into the mesh's shard-major layout
    spec8 = t8.model.ps_specs()["categorical"]
    tbl1 = s1.tables["categorical"]
    from jax.sharding import PartitionSpec as P
    shardings = jax.tree_util.tree_map(
        lambda p: NamedSharding(t8.mesh, p), t8._table_pspec(spec8),
        is_leaf=lambda x: isinstance(x, P))
    # np.asarray forces copies — step1 donates s1's buffers, s8 must not alias them
    tbl8 = s8.tables["categorical"].replace(
        weights=jax.device_put(np.asarray(interleave_rows(tbl1.weights, 8)),
                               shardings.weights),
        slots={k: jax.device_put(np.asarray(interleave_rows(v, 8)),
                                 shardings.slots[k])
               for k, v in tbl1.slots.items()})
    rep = NamedSharding(t8.mesh, P())
    host = jax.tree_util.tree_map(np.asarray, (s1.dense_params, s1.dense_slots))
    s8 = s8.replace(tables={"categorical": tbl8},
                    dense_params=jax.device_put(host[0], rep),
                    dense_slots=jax.device_put(host[1], rep))

    step1 = t1.jit_train_step()
    step8 = t8.jit_train_step(batch, s8)
    l1s, l8s = [], []
    for i in range(3):
        b = _ctr_batch(B=64, seed=10 + i)
        s1, m1 = step1(s1, b)
        s8, m8 = step8(s8, b)
        l1s.append(float(m1["loss"]) / 8.0)  # undo the 8x loss scale for reporting
        l8s.append(float(m8["loss"]))
    np.testing.assert_allclose(l1s, l8s, rtol=1e-5)


def test_two_tower_trains():
    model = make_two_tower(VOCAB, VOCAB, dim=4, tower=(16, 8),
                           compute_dtype=jnp.float32)
    B = 16
    rng = np.random.default_rng(0)
    batch = {
        "sparse": {"user": rng.integers(0, VOCAB, (B, 3)),
                   "item": rng.integers(0, VOCAB, (B, 2))},
        "dense": None,
        "label": np.zeros((B,), np.float32),
    }
    batch = {k: v for k, v in batch.items() if v is not None}
    losses = _smoke_train(model, batch, steps=5)
    assert losses[-1] < losses[0] + 0.5  # in-batch softmax is finite and sane


# -- data pipeline ----------------------------------------------------------


def test_hash_category_field_salting():
    toks = np.array([7, 7], dtype=np.uint64)
    fields = np.array([0, 1], dtype=np.uint64)
    ids = hash_category(toks, fields, 1 << 20)
    assert ids[0] != ids[1]  # same token, different field -> different id
    assert (ids >= 0).all()


def test_synthetic_criteo_shapes_and_skew():
    it = synthetic_criteo(1024, id_space=1 << 20, steps=1, seed=0)
    b = next(it)
    assert b["sparse"]["categorical"].shape == (1024, 26)
    assert b["dense"].shape == (1024, 13)
    assert b["label"].shape == (1024,)
    # Zipf skew: the most frequent id should repeat
    _, counts = np.unique(b["sparse"]["categorical"], return_counts=True)
    assert counts.max() > 5


CRITEO_ROW = ("1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t"
              + "\t".join(f"{i:08x}" for i in range(26)))


def test_read_criteo_tsv(tmp_path):
    p = tmp_path / "day0.tsv"
    rows = []
    for r in range(10):
        cols = CRITEO_ROW.split("\t")
        cols[0] = str(r % 2)
        cols[3] = ""          # missing dense value
        cols[20] = ""         # missing categorical
        rows.append("\t".join(cols))
    p.write_text("\n".join(rows) + "\n")
    batches = list(read_criteo_tsv(str(p), 4, id_space=1 << 16,
                                   drop_remainder=False))
    assert len(batches) == 3
    assert batches[0]["sparse"]["categorical"].shape == (4, 26)
    assert batches[2]["label"].shape == (2,)
    assert np.isfinite(batches[0]["dense"]).all()
    # host sharding partitions rows
    h0 = list(read_criteo_tsv(str(p), 1, host_id=0, num_hosts=2))
    h1 = list(read_criteo_tsv(str(p), 1, host_id=1, num_hosts=2))
    assert len(h0) == 5 and len(h1) == 5
    assert h0[0]["label"][0] == 0.0 and h1[0]["label"][0] == 1.0


def test_criteo_batcher_pads():
    def gen():
        yield {"sparse": {"categorical": np.ones((3, 2), np.int64)},
               "dense": np.ones((3, 4), np.float32),
               "label": np.ones((3,), np.float32)}
    out = list(CriteoBatcher(gen(), 8))
    assert out[0]["label"].shape == (8,)
    assert (out[0]["sparse"]["categorical"][3:] == -1).all()
    assert (out[0]["label"][3:] == 0).all()
    np.testing.assert_array_equal(out[0]["weight"],
                                  [1, 1, 1, 0, 0, 0, 0, 0])


def test_criteo_batcher_splits_and_carries():
    """Oversized incoming batches are split; remainders carry across batches."""
    def gen():
        for start in (0, 5):  # two ragged batches of 5 rows each
            yield {"sparse": {"categorical":
                              np.arange(start, start + 5).reshape(5, 1)},
                   "dense": np.zeros((5, 2), np.float32),
                   "label": np.arange(start, start + 5, dtype=np.float32)}
    out = list(CriteoBatcher(gen(), 4))
    assert [b["label"].shape[0] for b in out] == [4, 4, 4]
    got = np.concatenate([b["label"] for b in out])
    np.testing.assert_array_equal(got[:10], np.arange(10, dtype=np.float32))
    np.testing.assert_array_equal(out[-1]["weight"], [1, 1, 0, 0])
    assert (out[-1]["sparse"]["categorical"][2:] == -1).all()


def test_weighted_loss_ignores_padding():
    """A padded batch must produce the same loss/update as the unpadded one."""
    model = make_deepfm(VOCAB, dim=4, hidden=(8,), compute_dtype=jnp.float32)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.1))
    full = _ctr_batch(B=16, seed=2)
    state = tr.init(full)
    # same rows padded out to 32 with weight 0 / id -1
    padded = {
        "sparse": {"categorical": np.concatenate(
            [full["sparse"]["categorical"],
             np.full((16, 26), -1, np.int64)])},
        "dense": np.concatenate([full["dense"], np.zeros((16, 13), np.float32)]),
        "label": np.concatenate([full["label"], np.zeros((16,), np.float32)]),
        "weight": np.concatenate([np.ones((16,), np.float32),
                                  np.zeros((16,), np.float32)]),
    }
    l_full = float(tr.eval_step(state, full)["loss"])
    l_pad = float(tr.eval_step(state, padded)["loss"])
    np.testing.assert_allclose(l_full, l_pad, rtol=1e-6)


def test_graft_entry_contract():
    """The driver contract: entry() compiles single-device; dryrun_multichip(8)
    compiles + executes on the virtual mesh."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft", "/root/repo/__graft_entry__.py")
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    fn, args = m.entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(float(out["loss"]))
    m.dryrun_multichip(8)


# ---------------------------------------------------------------------------
# split first-order layout (EmbeddingSpec.feature aliasing)
# ---------------------------------------------------------------------------


def test_split_first_order_trains_and_serves(tmp_path):
    """first_order="split": two variables share the CATEGORICAL id feature;
    training, export, and predict all work without the batch carrying a
    "first_order" key."""
    from openembedding_tpu.export import StandaloneModel, export_standalone

    model = make_deepfm(vocabulary=VOCAB, dim=8, first_order="split")
    assert set(model.specs) == {"categorical", "first_order"}
    assert model.specs["first_order"].feature_name == "categorical"
    assert model.specs["categorical"].output_dim == 8
    b = _ctr_batch()
    losses = _smoke_train(model, b, steps=8)
    assert losses[-1] < losses[0]

    tr = Trainer(model, embed.Adagrad(learning_rate=0.05))
    state = tr.init(b)
    step = tr.jit_train_step()
    for _ in range(3):
        state, _ = step(state, b)
    path = str(tmp_path / "split_export")
    export_standalone(state, model, path)
    sm = StandaloneModel.load(path)
    logits = np.asarray(sm.predict({"sparse": b["sparse"],
                                    "dense": b["dense"]}))
    ev = tr.jit_eval_step()(state, b)
    np.testing.assert_allclose(logits, np.asarray(ev["logits"]),
                               rtol=1e-5, atol=1e-5)


def test_split_first_order_auto_and_packing():
    """auto: dim 9 folds (packed width 20), dim 64 splits (widths 128 + 2 —
    both lane-clean for the packed scan layout)."""
    from openembedding_tpu.ops.sparse import packed_layout

    m9 = make_deepfm(vocabulary=256, dim=9)
    assert list(m9.specs) == ["categorical"]
    assert m9.specs["categorical"].output_dim == 10

    m64 = make_deepfm(vocabulary=256, dim=64)
    assert set(m64.specs) == {"categorical", "first_order"}
    opt = embed.Adagrad(learning_rate=0.05)
    for name, spec in m64.specs.items():
        slots = opt.init_slots(4, spec.output_dim)
        assert packed_layout(spec.output_dim, slots) is not None, name


def test_split_first_order_mesh_and_config_roundtrip():
    """Split layout through the sharded mesh path, and from_config rebuilds
    the same two-variable structure."""
    from openembedding_tpu.models import from_config

    model = make_deepfm(vocabulary=VOCAB, dim=8, first_order="split")
    rebuilt = from_config(model.config)
    assert set(rebuilt.specs) == set(model.specs)
    assert rebuilt.specs["first_order"].feature_name == "categorical"

    mesh = make_mesh()
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=mesh)
    b = _ctr_batch()
    state = tr.init(b)
    step = tr.jit_train_step(b, state)
    losses = []
    for _ in range(3):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
