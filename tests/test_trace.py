"""Trace core tests (`utils/trace.py`): span nesting (same-thread and across
threads), ring-buffer eviction, histogram quantile accuracy, Chrome-trace
export, request-id propagation through a live serving request, /statusz and
/tracez, and the tools/trace_report.py smoke."""

import contextvars
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from openembedding_tpu.utils import metrics, trace


@pytest.fixture(autouse=True)
def _fresh():
    metrics._REGISTRY.clear()
    trace.RECORDER.clear()
    yield
    metrics._REGISTRY.clear()
    trace.RECORDER.clear()


# -- span core ----------------------------------------------------------------


def test_span_nesting_and_request_id():
    with trace.request("req-1"):
        with trace.span("g", "outer", foo=1) as outer:
            with trace.span("g", "inner") as inner:
                assert trace.current_span() is inner
            assert trace.current_span() is outer
    assert trace.current_span() is None
    spans = trace.RECORDER.spans()
    # completion order: inner lands before outer
    assert [(s.name, s.trace_id) for s in spans] == [("inner", "req-1"),
                                                     ("outer", "req-1")]
    inner, outer = spans
    assert inner.parent_id == outer.span_id
    assert outer.parent_id is None
    assert outer.attrs == {"foo": 1}
    assert outer.duration_ms >= inner.duration_ms >= 0
    # every span doubles as a latency histogram observation
    assert metrics.Accumulator.get("g.outer.ms", "hist").count == 1


def test_span_nesting_across_threads():
    """A thread launched with copy_context() nests under the launching span;
    a bare thread starts a fresh trace (no parent, no inherited id)."""
    results = {}

    def child():
        with trace.span("g", "child"):
            pass
        results["rid"] = trace.get_request_id()

    with trace.request("req-t"):
        with trace.span("g", "parent") as parent:
            ctx = contextvars.copy_context()
            t = threading.Thread(target=ctx.run, args=(child,))
            t.start()
            t.join()
    child_span = next(s for s in trace.RECORDER.spans() if s.name == "child")
    assert child_span.parent_id == parent.span_id
    assert child_span.trace_id == "req-t"
    assert results["rid"] == "req-t"

    trace.RECORDER.clear()
    t = threading.Thread(target=child)  # no context handoff
    t.start()
    t.join()
    bare = trace.RECORDER.spans()[0]
    assert bare.parent_id is None and bare.trace_id is None
    assert results["rid"] is None


def test_span_records_error_and_reraises():
    with pytest.raises(ValueError):
        with trace.span("g", "boom"):
            raise ValueError("no")
    s = trace.RECORDER.spans()[0]
    assert s.attrs["error"] == "ValueError: no"
    # error exits are greppable: status attr + a flight-recorder event that
    # survives span-ring eviction
    assert s.attrs["status"] == "error"
    assert s.duration_ms is not None
    evs = [e for e in trace.RECORDER.tail() if e.name == "span_error"]
    assert len(evs) == 1
    assert evs[0].group == "g"
    assert evs[0].attrs == {"span": "boom", "error": "ValueError: no"}
    # clean exits don't get the status attr or the event
    with trace.span("g", "fine"):
        pass
    ok = next(s for s in trace.RECORDER.spans() if s.name == "fine")
    assert "status" not in ok.attrs
    assert len([e for e in trace.RECORDER.tail()
                if e.name == "span_error"]) == 1


def test_flight_recorder_eviction_order():
    rec = trace.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record(trace.Event("g", f"e{i}", {}))
    names = [e.name for e in rec.tail()]
    assert names == ["e6", "e7", "e8", "e9"]  # oldest evicted, order kept
    rec.configure(2)
    assert [e.name for e in rec.tail()] == ["e8", "e9"]  # newest survive
    assert rec.capacity == 2


def test_events_and_render_text():
    trace.event("sync", "state", frm="IDLE", to="DEGRADED", reason="torn")
    with trace.span("g", "s"):
        pass
    text = trace.RECORDER.render_text()
    assert "EVT  sync.state" in text and "reason=torn" in text
    assert "SPAN g.s" in text


# -- histogram quantiles ------------------------------------------------------


def test_histogram_quantiles_match_numpy():
    """Log-spaced buckets + in-bucket interpolation: p50/p95/p99 within a
    bucket-width (sqrt2) relative tolerance of exact numpy percentiles on a
    known heavy-tailed latency distribution."""
    rng = np.random.default_rng(7)
    vals = rng.lognormal(mean=1.0, sigma=1.2, size=8000)
    acc = metrics.Accumulator.get("q.lat.ms", "hist")
    for v in vals:
        acc.observe(v)
    for q in (0.5, 0.95, 0.99):
        exact = float(np.percentile(vals, q * 100))
        got = acc.quantile(q)
        assert abs(got - exact) <= 0.25 * exact, (q, got, exact)
    # degenerate cases: empty -> 0, single value -> that value (clamping)
    empty = metrics.Accumulator.get("q.none.ms", "hist")
    assert empty.quantile(0.5) == 0.0
    one = metrics.Accumulator.get("q.one.ms", "hist")
    one.observe(3.25)
    assert one.quantile(0.5) == pytest.approx(3.25)


# -- chrome export + report tool ----------------------------------------------


def _load_tool(name):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_dump_chrome_and_trace_report(tmp_path, capsys):
    with trace.request("req-d"):
        with trace.span("serving", "http"):
            with trace.span("serving", "predict", model="m"):
                pass
    trace.event("persist", "commit", step=3)
    path = trace.dump_chrome(str(tmp_path / "dump.json"))

    with open(path) as f:
        doc = json.load(f)  # valid Chrome-trace JSON
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    instants = [e for e in evs if e["ph"] == "i"]
    assert {e["name"] for e in xs} == {"serving.http", "serving.predict"}
    assert instants[0]["name"] == "persist.commit"
    for e in xs:
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["args"]["request_id"] == "req-d"
        assert {"pid", "tid", "cat"} <= set(e)
    child = next(e for e in xs if e["name"] == "serving.predict")
    parent = next(e for e in xs if e["name"] == "serving.http")
    assert child["args"]["parent_id"] == parent["args"]["span_id"]

    # tier-1-riding smoke for tools/trace_report.py on the same dump
    tr = _load_tool("trace_report")
    rows = tr.report(tr.load_events(path))
    assert {r["key"] for r in rows} == {"serving.http", "serving.predict"}
    for r in rows:
        assert r["count"] == 1
        assert r["p99_ms"] >= r["p50_ms"] >= 0
    table = tr.format_table(rows)
    assert "serving.http" in table and "p99_ms" in table
    assert tr.main([path, "--by", "group", "--sort", "mean"]) == 0
    assert "serving" in capsys.readouterr().out


# -- live serving: request-id propagation + /statusz + /tracez ----------------


@pytest.fixture()
def served_model(tmp_path):
    """A serving node with micro-batching ON and a tiny deepfm loaded."""
    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.export import export_standalone
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.serving import make_server

    model = make_deepfm(vocabulary=256, dim=4, hidden=(8,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batch = next(iter(synthetic_criteo(8, id_space=256, steps=1, seed=0)))
    state = trainer.init(batch)
    export_dir = str(tmp_path / "export")
    export_standalone(state, model, export_dir, model_sign="t-0")
    srv = make_server(str(tmp_path / "reg"), port=0, batch_window_ms=2.0)
    srv.manager.load_model("t-0", export_dir)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv, batch
    srv.shutdown()


def test_request_id_propagates_through_live_predict(served_model):
    """ONE predict request yields >= 4 nested spans (http -> predict ->
    batch exec -> model call, plus queue wait) all correlated by the
    caller's X-OETPU-Request-Id, which the response echoes; /metrics gains
    the predict-latency histogram."""
    base, srv, batch = served_model
    body = json.dumps({
        "sparse": {"categorical":
                   np.asarray(batch["sparse"]["categorical"]).tolist()},
        "dense": np.asarray(batch["dense"]).tolist()}).encode()
    req = urllib.request.Request(
        f"{base}/models/t-0/predict", data=body, method="POST",
        headers={"Content-Type": "application/json",
                 "X-OETPU-Request-Id": "req-e2e"})
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 200
        assert resp.headers["X-OETPU-Request-Id"] == "req-e2e"
        json.loads(resp.read())

    with urllib.request.urlopen(f"{base}/tracez") as resp:
        tz = json.loads(resp.read())
    spans = {s["span_id"]: s for s in tz["spans"]
             if s["request_id"] == "req-e2e"}
    names = {s["name"] for s in spans.values()}
    assert {"http", "predict", "queue_wait", "batch_exec",
            "model_call"} <= names
    assert len(spans) >= 4

    # parent chain: model_call -> batch_exec -> predict -> http (depth 4)
    def chain(s):
        out = [s["name"]]
        while s["parent_id"] in spans:
            s = spans[s["parent_id"]]
            out.append(s["name"])
        return out

    mc = next(s for s in spans.values() if s["name"] == "model_call")
    assert chain(mc) == ["model_call", "batch_exec", "predict", "http"]
    qw = next(s for s in spans.values() if s["name"] == "queue_wait")
    assert chain(qw) == ["queue_wait", "predict", "http"]
    assert all(s["attrs"].get("status") == 200 for s in spans.values()
               if s["name"] == "http")

    with urllib.request.urlopen(f"{base}/metrics") as resp:
        text = resp.read().decode()
    assert 'oetpu_serving_predict_ms_bucket{model="t-0",le="+Inf"} 1' in text
    assert 'oetpu_serving_predict_ms_count{model="t-0"} 1' in text
    assert "oetpu_serving_http_ms_bucket" in text


def test_statusz_and_tracez_surfaces(served_model):
    base, srv, batch = served_model
    with urllib.request.urlopen(f"{base}/statusz") as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()
    assert "== openembedding_tpu serving /statusz ==" in text
    assert "t-0: step=0 kind=StandaloneModel status=NORMAL" in text
    assert "-- sync subscribers --" in text
    assert "-- workload skew (hot ids) --" in text
    assert "-- flight recorder" in text
    # a request id was generated for the statusz request itself. The http
    # span closes (and records) just AFTER the response body is written, so
    # an immediate /tracez can race it by ~1 ms — poll briefly.
    deadline = time.time() + 5.0
    while True:
        with urllib.request.urlopen(f"{base}/tracez?n=8") as resp:
            tz = json.loads(resp.read())
        if any(s["name"] == "http" and s["request_id"]
               for s in tz["spans"]):
            break
        assert time.time() < deadline, tz["spans"]
        time.sleep(0.01)


def test_trainer_phase_histograms_on_metrics(served_model):
    """An (eager) train step records trainer.{pull,compute,apply} phase
    spans; /metrics then exposes them as histogram series."""
    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm

    base, srv, _ = served_model
    model = make_deepfm(vocabulary=128, dim=4, hidden=(8,))
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batch = next(iter(synthetic_criteo(4, id_space=128, steps=1, seed=2)))
    state = trainer.init(batch)
    trainer.train_step(state, batch)  # eager: spans time real execution
    with urllib.request.urlopen(f"{base}/metrics") as resp:
        text = resp.read().decode()
    for phase in ("pull", "compute", "apply"):
        assert f"# TYPE oetpu_trainer_{phase}_ms histogram" in text
        assert f"oetpu_trainer_{phase}_ms_count 1" in text
