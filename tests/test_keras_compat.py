"""Whole-model Keras conversion + import-hook injection
(`keras_compat.from_keras_model`, `python -m openembedding_tpu.inject`).

Reference surfaces: `distributed_model()`'s clone-replace of live Keras graphs
(`tensorflow/exb.py:593-642`) and the laboratory's interpreter-startup
monkeypatch (`laboratory/inject/openembedding_inject_tensorflow.py`).

Keras backends are fixed at first import, and this suite's process imports
keras with the TF backend (test_keras_parity needs it) — so every scenario
here runs in a FRESH subprocess with KERAS_BACKEND=jax."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, env_extra=None, timeout=600):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env.update({"KERAS_BACKEND": "jax", "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO,
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    env.update(env_extra or {})
    p = subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                       capture_output=True, text=True, timeout=timeout,
                       env=env)
    assert p.returncode == 0, f"STDOUT:\n{p.stdout}\nSTDERR:\n{p.stderr}"
    return p.stdout


def test_conversion_forward_parity_and_one_step():
    """The converted model must PREDICT exactly what the Keras model predicts
    (same rows imported, same dense weights by construction), and one SGD
    step must move the dense kernel the way Keras's own fit does."""
    out = _run("""
        import numpy as np, keras, jax
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import (from_keras_model,
            import_keras_rows)
        from openembedding_tpu.model import Trainer

        cat = keras.Input(shape=(4,), dtype="int32", name="cat")
        wide = keras.Input(shape=(3,), name="wide")
        emb = keras.layers.Embedding(500, 8, name="emb1")(cat)
        x = keras.layers.Flatten()(emb)
        x = keras.layers.Concatenate()([x, wide])
        x = keras.layers.Dense(16, activation="relu")(x)
        out = keras.layers.Dense(1, activation="sigmoid")(x)
        m = keras.Model([cat, wide], out)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 500, (64, 4)).astype(np.int32)
        w = rng.standard_normal((64, 3)).astype(np.float32)
        y = rng.integers(0, 2, (64,)).astype(np.float32)

        emodel, _ = from_keras_model(m)
        trainer = Trainer(emodel, embed.SGD(learning_rate=0.1))
        batch = {"sparse": {"cat": ids}, "dense": w, "label": y}
        state = trainer.init(batch)
        state = import_keras_rows(trainer, state, m)

        want = np.asarray(m([ids, w])).reshape(-1)
        got = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        print("FORWARD_PARITY_OK")

        # one SGD step vs keras fit (same loss: BCE on probabilities)
        state, _ = trainer.jit_train_step()(state, batch)
        m.compile(optimizer=keras.optimizers.SGD(learning_rate=0.1),
                  loss="binary_crossentropy")
        m.fit([ids, w], y, batch_size=64, epochs=1, shuffle=False, verbose=0)
        kd = np.asarray([v.value for v in m.trainable_variables
                         if tuple(v.shape) == (35, 16)][0])
        ours = np.asarray(state.dense_params["v0"]
                          if tuple(state.dense_params["v0"].shape) == (35, 16)
                          else state.dense_params["v1"])
        np.testing.assert_allclose(ours, kd, rtol=1e-4, atol=1e-5)
        print("ONE_STEP_PARITY_OK")
    """)
    assert "FORWARD_PARITY_OK" in out and "ONE_STEP_PARITY_OK" in out


def test_conversion_guards():
    """Backend + structure guards fail fast with actionable messages."""
    out = _run("""
        import numpy as np, keras
        from openembedding_tpu.keras_compat import from_keras_model

        # no embedding layers
        m = keras.Sequential([keras.Input((4,)), keras.layers.Dense(1)])
        try:
            from_keras_model(m)
        except ValueError as e:
            assert "Embedding" in str(e)
            print("NO_EMB_GUARD_OK")

        # embedding fed by an intermediate, not an Input
        ids = keras.Input(shape=(4,), dtype="int32", name="ids")
        shifted = keras.layers.Lambda(lambda t: t)(ids)
        emb = keras.layers.Embedding(10, 4)(shifted)
        m2 = keras.Model(ids, keras.layers.Dense(1)(
            keras.layers.Flatten()(emb)))
        try:
            from_keras_model(m2)
        except ValueError as e:
            assert "Input" in str(e)
            print("INTERMEDIATE_GUARD_OK")
    """)
    assert "NO_EMB_GUARD_OK" in out and "INTERMEDIATE_GUARD_OK" in out


def test_batchnorm_model_conversion_parity():
    """A BN-bearing tower (DeepCTR's DNN block uses BatchNorm) converts: the
    frozen moving stats ride in dense_params, advance from the training
    forward pass, and after 3 identical SGD steps both the trainable weights
    and the BN moving stats match Keras's own fit (reference converts such
    graphs freely, `exb.py:593-642`)."""
    out = _run("""
        import numpy as np, keras
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import (from_keras_model,
            import_keras_rows)
        from openembedding_tpu.model import Trainer

        cat = keras.Input(shape=(4,), dtype="int32", name="cat")
        wide = keras.Input(shape=(3,), name="wide")
        emb = keras.layers.Embedding(300, 8, name="emb1")(cat)
        x = keras.layers.Flatten()(emb)
        x = keras.layers.Concatenate()([x, wide])
        x = keras.layers.Dense(16)(x)
        x = keras.layers.BatchNormalization(name="bn")(x)
        x = keras.layers.ReLU()(x)
        out = keras.layers.Dense(1, activation="sigmoid")(x)
        m = keras.Model([cat, wide], out)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 300, (64, 4)).astype(np.int32)
        w = rng.standard_normal((64, 3)).astype(np.float32)
        y = rng.integers(0, 2, (64,)).astype(np.float32)

        emodel, _ = from_keras_model(m)
        trainer = Trainer(emodel, embed.SGD(learning_rate=0.1))
        batch = {"sparse": {"cat": ids}, "dense": w, "label": y}
        state = trainer.init(batch)
        state = import_keras_rows(trainer, state, m)

        want = np.asarray(m([ids, w], training=False)).reshape(-1)
        got = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        print("BN_FORWARD_OK")

        step = trainer.jit_train_step()
        for _ in range(3):
            state, _ = step(state, batch)

        m.compile(optimizer=keras.optimizers.SGD(learning_rate=0.1),
                  loss="binary_crossentropy")
        m.fit([ids, w], y, batch_size=64, epochs=3, shuffle=False, verbose=0)

        dm = emodel.module.dense_model
        for i, v in enumerate(dm.trainable_variables):
            np.testing.assert_allclose(
                np.asarray(state.dense_params[f"v{i}"]),
                np.asarray(v.value), rtol=1e-3, atol=1e-5)
        moved = 0
        for i, v in enumerate(dm.non_trainable_variables):
            ours = np.asarray(state.dense_params[f"n{i}"])
            np.testing.assert_allclose(ours, np.asarray(v.value),
                                       rtol=1e-3, atol=1e-5)
            moved += int(not np.allclose(
                ours, np.zeros_like(ours)) and "mean" in v.path)
        # the moving mean really moved off its 0.0 init (stats are LIVE)
        assert moved >= 1, [v.path for v in dm.non_trainable_variables]
        print("BN_TRAIN_PARITY_OK")
    """)
    assert "BN_FORWARD_OK" in out and "BN_TRAIN_PARITY_OK" in out


def test_batchnorm_model_trains_on_mesh():
    """The frozen-state path under shard_map: BN moving stats are computed
    from LOCAL batch statistics per shard and pmean'd back to ONE replicated
    value (`MeshTrainer.reduce_module_state`). Asserts the stats move off
    init, stay finite, and every device replica holds the SAME bytes."""
    out = _run("""
        import numpy as np, keras
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import from_keras_model
        from openembedding_tpu.parallel import MeshTrainer, make_mesh

        cat = keras.Input(shape=(4,), dtype="int32", name="cat")
        emb = keras.layers.Embedding(512, 8, name="emb1")(cat)
        x = keras.layers.Flatten()(emb)
        x = keras.layers.Dense(16)(x)
        x = keras.layers.BatchNormalization(name="bn")(x)
        x = keras.layers.ReLU()(x)
        out = keras.layers.Dense(1, activation="sigmoid")(x)
        m = keras.Model(cat, out)

        emodel, _ = from_keras_model(m)
        tr = MeshTrainer(emodel, embed.SGD(learning_rate=0.1),
                         mesh=make_mesh())
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 512, (64, 4)).astype(np.int32)
        batch = {"sparse": {"cat": ids}, "dense": None,
                 "label": (ids[:, 0] % 2).astype(np.float32)}
        state = tr.init(batch)
        nt0 = {k: np.asarray(v) for k, v in state.dense_params.items()
               if k.startswith("n")}
        assert nt0, "BN model must carry frozen leaves"
        step = tr.jit_train_step(batch, state)
        losses = []
        for _ in range(20):
            state, mt = step(state, batch)
            losses.append(float(mt["loss"]))
        assert losses[-1] < losses[0], losses[::5]
        moved = 0
        for k, v in state.dense_params.items():
            if not k.startswith("n"):
                continue
            vals = [np.asarray(s.data) for s in v.addressable_shards]
            for other in vals[1:]:   # replicas bit-identical after pmean
                np.testing.assert_array_equal(vals[0], other, err_msg=k)
            assert np.isfinite(vals[0]).all(), k
            moved += int(not np.allclose(vals[0], nt0[k]))
        assert moved >= 2, moved  # moving mean AND variance advanced
        print("MESH_BN_OK")
    """)
    assert "MESH_BN_OK" in out


def test_shared_embedding_two_tower():
    """ONE Embedding layer applied at two call sites (two-tower retrieval
    shape) converts to ONE table: call-site id columns concatenate through
    `batch_transform`, rows slice back per site, and gradients from both
    towers accumulate into the same rows — matching Keras fit exactly."""
    out = _run("""
        import numpy as np, keras
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import (from_keras_model,
            import_keras_rows)
        from openembedding_tpu.model import Trainer

        user = keras.Input(shape=(2,), dtype="int32", name="user_hist")
        item = keras.Input(shape=(3,), dtype="int32", name="item_ids")
        shared = keras.layers.Embedding(400, 8, name="shared_emb")
        ue = keras.layers.Flatten()(shared(user))
        ie = keras.layers.Flatten()(shared(item))
        x = keras.layers.Concatenate()([ue, ie])
        x = keras.layers.Dense(16, activation="relu")(x)
        out = keras.layers.Dense(1, activation="sigmoid")(x)
        m = keras.Model([user, item], out)

        rng = np.random.default_rng(1)
        u = rng.integers(0, 400, (64, 2)).astype(np.int32)
        it = rng.integers(0, 400, (64, 3)).astype(np.int32)
        # overlap between towers so shared-row gradient accumulation is hit
        it[:, 0] = u[:, 0]
        y = rng.integers(0, 2, (64,)).astype(np.float32)

        emodel, _ = from_keras_model(m)
        assert emodel.batch_transform is not None
        trainer = Trainer(emodel, embed.SGD(learning_rate=0.1))
        batch = {"sparse": {"user_hist": u, "item_ids": it},
                 "dense": None, "label": y}
        state = trainer.init(batch)
        state = import_keras_rows(trainer, state, m)

        want = np.asarray(m([u, it], training=False)).reshape(-1)
        got = np.asarray(trainer.jit_eval_step()(state, batch)["logits"])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        print("SHARED_FORWARD_OK")

        step = trainer.jit_train_step()
        for _ in range(3):
            state, _ = step(state, batch)
        m.compile(optimizer=keras.optimizers.SGD(learning_rate=0.1),
                  loss="binary_crossentropy")
        m.fit([u, it], y, batch_size=64, epochs=3, shuffle=False, verbose=0)
        np.testing.assert_allclose(
            np.asarray(state.tables["shared_emb"].weights),
            np.asarray(m.get_layer("shared_emb").embeddings.value),
            rtol=1e-4, atol=1e-6)
        print("SHARED_TRAIN_OK")
    """)
    assert "SHARED_FORWARD_OK" in out and "SHARED_TRAIN_OK" in out


def test_inject_runner_trains_unmodified_script(tmp_path):
    """The reference's laboratory story end to end: a script written against
    plain Keras (build, compile, fit, predict) runs unmodified under
    `python -m openembedding_tpu.inject` — fit routes through the framework
    trainer, loss drops, and the script's own predict() sees the training."""
    script = tmp_path / "user_script.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import keras

        rng = np.random.default_rng(0)
        V, B, F = 300, 512, 4
        ids = rng.integers(0, V, (B, F)).astype(np.int32)
        # planted signal: label depends on the first id's parity
        y = (ids[:, 0] % 2).astype(np.float32)

        cat = keras.Input(shape=(F,), dtype="int32", name="cat")
        emb = keras.layers.Embedding(V, 8, name="emb")(cat)
        x = keras.layers.Flatten()(emb)
        x = keras.layers.Dense(16, activation="relu")(x)
        out = keras.layers.Dense(1, activation="sigmoid")(x)
        m = keras.Model(cat, out)
        m.compile(optimizer=keras.optimizers.Adagrad(learning_rate=0.5),
                  loss="binary_crossentropy")

        h = m.fit(ids, y, batch_size=64, epochs=8, verbose=0)
        losses = h.history["loss"]
        assert losses[-1] < losses[0] * 0.5, losses
        print("FIT_LOSSES", round(losses[0], 4), "->", round(losses[-1], 4))

        p = np.asarray(m(ids)).reshape(-1)
        acc = float(((p > 0.5) == (y > 0.5)).mean())
        assert acc > 0.9, acc
        print("PREDICT_AFTER_FIT_OK", round(acc, 3))
    """))
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                "OETPU_INJECT_DEBUG": "1"})
    p = subprocess.run(
        [sys.executable, "-m", "openembedding_tpu.inject", str(script)],
        capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, f"STDOUT:\n{p.stdout}\nSTDERR:\n{p.stderr}"
    assert "PREDICT_AFTER_FIT_OK" in p.stdout
    assert "[inject] routing fit" in p.stderr  # really went through the framework


def test_inject_mesh_trains(tmp_path):
    """OETPU_INJECT_MESH=1: the same unmodified script trains data-parallel
    with row-sharded tables over 8 virtual devices."""
    script = tmp_path / "user_script.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import keras

        rng = np.random.default_rng(0)
        V, B, F = 300, 512, 4
        ids = rng.integers(0, V, (B, F)).astype(np.int32)
        y = (ids[:, 0] % 2).astype(np.float32)

        cat = keras.Input(shape=(F,), dtype="int32", name="cat")
        emb = keras.layers.Embedding(V, 8, name="emb")(cat)
        x = keras.layers.Flatten()(emb)
        out = keras.layers.Dense(1, activation="sigmoid")(x)
        m = keras.Model(cat, out)
        m.compile(optimizer=keras.optimizers.Adagrad(learning_rate=0.5),
                  loss="binary_crossentropy")
        h = m.fit(ids, y, batch_size=64, epochs=6, verbose=0)
        losses = h.history["loss"]
        assert losses[-1] < losses[0] * 0.7, losses
        print("MESH_FIT_OK", round(losses[0], 4), "->", round(losses[-1], 4))
        # sharded rows deinterleave back into the Keras variables: the user's
        # own predict() reflects the mesh training
        p = np.asarray(m(ids)).reshape(-1)
        acc = float(((p > 0.5) == (y > 0.5)).mean())
        assert acc > 0.85, acc
        print("MESH_PREDICT_OK", round(acc, 3))
    """))
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "OETPU_INJECT_MESH": "1"})
    p = subprocess.run(
        [sys.executable, "-m", "openembedding_tpu.inject", str(script)],
        capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, f"STDOUT:\n{p.stdout}\nSTDERR:\n{p.stderr}"
    assert "MESH_FIT_OK" in p.stdout
    assert "MESH_PREDICT_OK" in p.stdout


def test_inject_fit_edge_semantics(tmp_path):
    """Partial trailing batches train (padded, weight-0 — matching Keras's
    mean over real rows), positional fit args bind, unsupported fit options
    raise instead of silently changing results, and a compiled 'mse' loss
    converts to the mse objective."""
    out = _run("""
        import numpy as np, keras
        from openembedding_tpu.inject import install
        install()

        rng = np.random.default_rng(0)
        V = 64
        ids = rng.integers(0, V, (100, 2)).astype(np.int32)  # 100 % 64 != 0
        y = (ids[:, 0] % 2).astype(np.float32)

        def build(loss, act):
            cat = keras.Input(shape=(2,), dtype="int32", name="cat")
            emb = keras.layers.Embedding(V, 4, name="emb")(cat)
            x = keras.layers.Flatten()(emb)
            out = keras.layers.Dense(1, activation=act)(x)
            m = keras.Model(cat, out)
            m.compile(optimizer=keras.optimizers.Adagrad(learning_rate=0.5),
                      loss=loss)
            return m

        # positional batch_size + partial tail batch
        m = build("binary_crossentropy", "sigmoid")
        h = m.fit(ids, y, 64, 4, 0)   # batch_size=64, epochs=4, verbose=0
        assert len(h.history["loss"]) == 4
        assert h.history["loss"][-1] < h.history["loss"][0], h.history
        print("POSITIONAL_AND_PARTIAL_OK")

        # n < batch_size: one padded batch still trains
        h2 = build("binary_crossentropy", "sigmoid").fit(
            ids[:20], y[:20], batch_size=64, epochs=3, verbose=0)
        assert h2.history["loss"][-1] < h2.history["loss"][0], h2.history
        print("SMALL_N_OK")

        # unsupported option -> explicit error, not silent divergence
        try:
            build("binary_crossentropy", "sigmoid").fit(
                ids, y, batch_size=64, epochs=1, verbose=0,
                class_weight={0: 1.0, 1: 5.0})
            raise SystemExit("class_weight should have raised")
        except ValueError as e:
            assert "class_weight" in str(e)
        print("UNSUPPORTED_KWARG_OK")

        # compiled mse trains the mse objective
        yreg = ids[:, 0].astype(np.float32) / V
        h3 = build("mse", None).fit(ids, yreg, batch_size=50, epochs=4,
                                    verbose=0)
        assert h3.history["loss"][-1] < h3.history["loss"][0], h3.history
        print("MSE_OK")

        # unsupported compiled loss -> explicit error
        try:
            build("categorical_crossentropy", None).fit(
                ids, y, batch_size=50, epochs=1, verbose=0)
            raise SystemExit("categorical loss should have raised")
        except ValueError as e:
            assert "not supported" in str(e)
        print("LOSS_GUARD_OK")
    """)
    for marker in ("POSITIONAL_AND_PARTIAL_OK", "SMALL_N_OK",
                   "UNSUPPORTED_KWARG_OK", "MSE_OK", "LOSS_GUARD_OK"):
        assert marker in out, out


def test_inject_callbacks_and_dataset_input(tmp_path):
    """Round-5 inject surface: REAL Keras callbacks drive off the synced live
    model (ModelCheckpoint saves per epoch, EarlyStopping stops the loop),
    and `x` may be a batch iterable — a re-iterable dataset (fresh pass per
    epoch) or a generator with steps_per_epoch."""
    ckdir = str(tmp_path / "ck")
    out = _run(f"""
        import numpy as np, os, keras
        from openembedding_tpu.inject import install
        install()

        rng = np.random.default_rng(0)
        V = 64
        ids = rng.integers(0, V, (96, 2)).astype(np.int32)
        y = (ids[:, 0] % 2).astype(np.float32)

        def build():
            cat = keras.Input(shape=(2,), dtype="int32", name="cat")
            emb = keras.layers.Embedding(V, 4, name="emb")(cat)
            x = keras.layers.Flatten()(emb)
            out = keras.layers.Dense(1, activation="sigmoid")(x)
            m = keras.Model(cat, out)
            m.compile(optimizer=keras.optimizers.Adagrad(learning_rate=0.5),
                      loss="binary_crossentropy", metrics=["AUC"])
            return m

        # ModelCheckpoint per epoch off the SYNCED live model
        os.makedirs({ckdir!r}, exist_ok=True)
        cb = keras.callbacks.ModelCheckpoint(
            {ckdir!r} + "/e{{epoch}}.weights.h5", save_weights_only=True)
        m = build()
        h = m.fit(ids, y, batch_size=32, epochs=3, verbose=0, callbacks=[cb])
        assert sorted(os.listdir({ckdir!r})) == [
            "e1.weights.h5", "e2.weights.h5", "e3.weights.h5"]
        assert "auc" in h.history and len(h.history["auc"]) == 3
        # epoch-1 weights differ from epoch-3 weights (real per-epoch saves)
        m.load_weights({ckdir!r} + "/e1.weights.h5")
        w1 = np.asarray(m.get_layer("emb").embeddings.value).copy()
        m.load_weights({ckdir!r} + "/e3.weights.h5")
        w3 = np.asarray(m.get_layer("emb").embeddings.value)
        assert not np.allclose(w1, w3)
        print("CHECKPOINT_CB_OK")

        # EarlyStopping: patience 0 on an always-worsening monitor stops at 1
        class Bomb(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                self.model.stop_training = True
        h2 = build().fit(ids, y, batch_size=32, epochs=5, verbose=0,
                         callbacks=[Bomb()])
        assert len(h2.history["loss"]) == 1, h2.history
        print("EARLY_STOP_OK")

        # re-iterable dataset input (list of (x, y) batches; fresh each epoch)
        batches = [({{"cat": ids[i:i+32]}}, y[i:i+32])
                   for i in range(0, 96, 32)]
        class DS:
            def __iter__(self): return iter(batches)
        h3 = build().fit(DS(), epochs=2, verbose=0)
        assert len(h3.history["loss"]) == 2
        assert h3.history["loss"][-1] < h3.history["loss"][0], h3.history
        print("DATASET_OK")

        # generator input needs steps_per_epoch; consumed ACROSS epochs
        def gen():
            while True:
                for b in batches:
                    yield b
        h4 = build().fit(gen(), epochs=2, steps_per_epoch=3, verbose=0)
        assert len(h4.history["loss"]) == 2
        print("GENERATOR_OK")
        try:
            build().fit(gen(), epochs=1, verbose=0)
            raise SystemExit("generator without steps_per_epoch should raise")
        except ValueError as e:
            assert "steps_per_epoch" in str(e)
        print("GENERATOR_GUARD_OK")
    """)
    for marker in ("CHECKPOINT_CB_OK", "EARLY_STOP_OK", "DATASET_OK",
                   "GENERATOR_OK", "GENERATOR_GUARD_OK"):
        assert marker in out, out


def test_shared_embedding_on_mesh():
    """batch_transform under shard_map: each shard concatenates ITS batch
    slice's call-site columns; forward parity vs the live Keras model with
    imported rows, then training moves the shared table."""
    out = _run("""
        import numpy as np, keras
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import (from_keras_model,
            import_keras_rows)
        from openembedding_tpu.parallel import MeshTrainer, make_mesh

        user = keras.Input(shape=(2,), dtype="int32", name="user_hist")
        item = keras.Input(shape=(3,), dtype="int32", name="item_ids")
        shared = keras.layers.Embedding(512, 8, name="shared_emb")
        x = keras.layers.Concatenate()([
            keras.layers.Flatten()(shared(user)),
            keras.layers.Flatten()(shared(item))])
        out = keras.layers.Dense(1, activation="sigmoid")(
            keras.layers.Dense(16, activation="relu")(x))
        m = keras.Model([user, item], out)

        rng = np.random.default_rng(2)
        u = rng.integers(0, 512, (64, 2)).astype(np.int32)
        it = rng.integers(0, 512, (64, 3)).astype(np.int32)
        y = (u[:, 0] % 2).astype(np.float32)

        emodel, _ = from_keras_model(m)
        tr = MeshTrainer(emodel, embed.SGD(learning_rate=0.1),
                         mesh=make_mesh())
        batch = {"sparse": {"user_hist": u, "item_ids": it},
                 "dense": None, "label": y}
        state = tr.init(batch)
        state = import_keras_rows(tr, state, m)
        want = np.asarray(m([u, it], training=False)).reshape(-1)
        got = np.asarray(tr.jit_eval_step(batch, state)(state, batch)["logits"])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

        step = tr.jit_train_step(batch, state)
        losses = []
        for _ in range(15):
            state, mt = step(state, batch)
            losses.append(float(mt["loss"]))
        assert losses[-1] < losses[0], losses[::5]
        print("MESH_SHARED_OK")
    """)
    assert "MESH_SHARED_OK" in out


def test_inject_shared_embedding_model():
    """Round-5 review regression: inject fit on a SHARED-Embedding model —
    the user batch is keyed by the feeding inputs' names, the synthesized
    layer-name feature exists only inside the jitted paths. This used to
    KeyError('shared_emb') in make_batch."""
    out = _run("""
        import numpy as np, keras
        from openembedding_tpu.inject import install
        install()

        user = keras.Input(shape=(2,), dtype="int32", name="user_hist")
        item = keras.Input(shape=(3,), dtype="int32", name="item_ids")
        shared = keras.layers.Embedding(200, 4, name="shared_emb")
        x = keras.layers.Concatenate()([
            keras.layers.Flatten()(shared(user)),
            keras.layers.Flatten()(shared(item))])
        out = keras.layers.Dense(1, activation="sigmoid")(
            keras.layers.Dense(8, activation="relu")(x))
        m = keras.Model([user, item], out)
        m.compile(keras.optimizers.Adagrad(learning_rate=0.5),
                  "binary_crossentropy")

        rng = np.random.default_rng(0)
        u = rng.integers(0, 200, (64, 2)).astype(np.int32)
        it = rng.integers(0, 200, (64, 3)).astype(np.int32)
        y = (u[:, 0] % 2).astype(np.float32)
        h = m.fit({"user_hist": u, "item_ids": it}, y, batch_size=32,
                  epochs=4, verbose=0)
        assert h.history["loss"][-1] < h.history["loss"][0], h.history
        print("INJECT_SHARED_OK")
    """)
    assert "INJECT_SHARED_OK" in out


def test_inject_runs_ported_hook_example(tmp_path):
    """The faithful port of the reference's hook script
    (`examples/criteo_deepctr_hook.py` -> ours) runs UNMODIFIED under
    `python -m openembedding_tpu.inject`: pandas -> hashed ids -> plain-Keras
    DeepFM -> fit(dict inputs, ModelCheckpoint, AUC metric) -> save."""
    import subprocess
    script = os.path.join(REPO, "examples", "criteo_deepctr_hook.py")
    ck = str(tmp_path / "hook_ck") + "/"
    saved = str(tmp_path / "hook.keras")
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env.update({"KERAS_BACKEND": "jax", "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO})
    p = subprocess.run(
        [sys.executable, "-m", "openembedding_tpu.inject", script,
         "--epochs", "2", "--checkpoint", ck, "--save", saved],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert p.returncode == 0, f"STDOUT:\n{p.stdout}\nSTDERR:\n{p.stderr}"
    assert "epoch 2/2" in p.stdout and "auc" in p.stdout, p.stdout
    assert sorted(os.listdir(ck)) == ["1.weights.h5", "2.weights.h5"]
    assert os.path.exists(saved)


def test_mesh_import_forward_parity():
    """Warm-start on a mesh: the Keras table interleaves into the row-sharded
    layout and the converted model predicts EXACTLY what Keras predicts
    before any training."""
    out = _run("""
        import numpy as np, keras
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import (from_keras_model,
            import_keras_rows)
        from openembedding_tpu.parallel import MeshTrainer, make_mesh

        V = 500  # not a multiple of 8: exercises the interleave padding
        cat = keras.Input(shape=(4,), dtype="int32", name="cat")
        emb = keras.layers.Embedding(V, 8, name="emb1")(cat)
        x = keras.layers.Flatten()(emb)
        out = keras.layers.Dense(1, activation="sigmoid")(x)
        m = keras.Model(cat, out)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, (64, 4)).astype(np.int32)
        y = rng.integers(0, 2, (64,)).astype(np.float32)
        batch = {"sparse": {"cat": ids}, "dense": None, "label": y}

        emodel, _ = from_keras_model(m)
        tr = MeshTrainer(emodel, embed.SGD(learning_rate=0.1),
                         mesh=make_mesh())
        state = tr.init(batch)
        state = import_keras_rows(tr, state, m)
        got = np.asarray(tr.jit_eval_step(batch, state)(state, batch)["logits"])
        want = np.asarray(m(ids)).reshape(-1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        print("MESH_IMPORT_PARITY_OK")
    """)
    assert "MESH_IMPORT_PARITY_OK" in out


def test_sequential_model_conversion_and_fit():
    """keras.Sequential (the most common unmodified-script shape): converts,
    trains through the framework, predict reflects training."""
    out = _run("""
        import numpy as np, keras
        from openembedding_tpu.inject import install
        install()

        rng = np.random.default_rng(0)
        V = 200
        ids = rng.integers(0, V, (256, 3)).astype(np.int32)
        y = (ids[:, 0] % 2).astype(np.float32)

        m = keras.Sequential([
            keras.Input(shape=(3,), dtype="int32", name="cat"),
            keras.layers.Embedding(V, 8, name="emb"),
            keras.layers.Flatten(),
            keras.layers.Dense(16, activation="relu"),
            keras.layers.Dense(1, activation="sigmoid"),
        ])
        m.compile(optimizer=keras.optimizers.Adagrad(learning_rate=0.5),
                  loss="binary_crossentropy")
        h = m.fit(ids, y, batch_size=64, epochs=8, verbose=0)
        assert h.history["loss"][-1] < h.history["loss"][0] * 0.5, h.history
        p = np.asarray(m(ids)).reshape(-1)
        acc = float(((p > 0.5) == (y > 0.5)).mean())
        assert acc > 0.9, acc
        print("SEQUENTIAL_OK", round(acc, 3))
    """)
    assert "SEQUENTIAL_OK" in out


def test_multi_embedding_functional_model():
    """DeepCTR-shaped graphs: several Embedding layers on several Inputs (a
    user table + an item table) convert into separate framework tables and
    predict exactly like Keras after row import."""
    out = _run("""
        import numpy as np, keras
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import (from_keras_model,
            import_keras_rows)
        from openembedding_tpu.model import Trainer

        u = keras.Input(shape=(2,), dtype="int32", name="user_ids")
        it = keras.Input(shape=(3,), dtype="int32", name="item_ids")
        ue = keras.layers.Embedding(300, 8, name="user_emb")(u)
        ie = keras.layers.Embedding(500, 8, name="item_emb")(it)
        x = keras.layers.Concatenate()([keras.layers.Flatten()(ue),
                                        keras.layers.Flatten()(ie)])
        x = keras.layers.Dense(16, activation="relu")(x)
        out = keras.layers.Dense(1, activation="sigmoid")(x)
        m = keras.Model([u, it], out)

        emodel, _ = from_keras_model(m)
        assert set(emodel.specs) == {"user_emb", "item_emb"}
        assert emodel.specs["user_emb"].feature_name == "user_ids"
        assert emodel.specs["item_emb"].feature_name == "item_ids"

        rng = np.random.default_rng(0)
        uid = rng.integers(0, 300, (32, 2)).astype(np.int32)
        iid = rng.integers(0, 500, (32, 3)).astype(np.int32)
        y = rng.integers(0, 2, (32,)).astype(np.float32)
        batch = {"sparse": {"user_ids": uid, "item_ids": iid},
                 "dense": None, "label": y}
        tr = Trainer(emodel, embed.Adagrad(learning_rate=0.1))
        state = tr.init(batch)
        state = import_keras_rows(tr, state, m)
        got = np.asarray(tr.jit_eval_step()(state, batch)["logits"])
        want = np.asarray(m([uid, iid])).reshape(-1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # and it trains
        state, mtr = tr.jit_train_step()(state, batch)
        assert np.isfinite(float(mtr["loss"]))
        print("MULTI_EMB_OK")
    """)
    assert "MULTI_EMB_OK" in out


def test_converted_model_checkpoint_roundtrip(tmp_path):
    """The full user journey keeps working through the converter: train a
    converted Keras model, checkpoint with the Trainer, restore into a FRESH
    conversion of the same architecture, predictions identical."""
    out = _run(f"""
        import numpy as np, keras
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import from_keras_model
        from openembedding_tpu.model import Trainer

        def build():
            cat = keras.Input(shape=(3,), dtype="int32", name="cat")
            emb = keras.layers.Embedding(200, 8, name="emb")(cat)
            x = keras.layers.Flatten()(emb)
            x = keras.layers.Dense(16)(x)
            x = keras.layers.BatchNormalization(name="bn")(x)
            x = keras.layers.ReLU()(x)
            out = keras.layers.Dense(1, activation="sigmoid")(x)
            return keras.Model(cat, out)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 200, (64, 3)).astype(np.int32)
        y = (ids[:, 0] % 2).astype(np.float32)
        batch = {{"sparse": {{"cat": ids}}, "dense": None, "label": y}}

        emodel, _ = from_keras_model(build())
        tr = Trainer(emodel, embed.Adagrad(learning_rate=0.3))
        state = tr.init(batch)
        step = tr.jit_train_step()
        for _ in range(10):
            state, m = step(state, batch)
        want = np.asarray(tr.jit_eval_step()(state, batch)["logits"])
        nt_want = {{k: np.asarray(v) for k, v in state.dense_params.items()
                    if k.startswith("n")}}
        assert nt_want, "BN model must carry frozen leaves"
        tr.save(state, {str(tmp_path / "ck")!r})

        emodel2, _ = from_keras_model(build())
        tr2 = Trainer(emodel2, embed.Adagrad(learning_rate=0.3))
        state2 = tr2.init(batch)
        state2 = tr2.load(state2, {str(tmp_path / "ck")!r})
        # the frozen (BN moving-stat) leaves restored bit-exactly — inference
        # after restart normalizes with the TRAINED statistics
        for k, v in nt_want.items():
            np.testing.assert_array_equal(
                np.asarray(state2.dense_params[k]), v, err_msg=k)
        got = np.asarray(tr2.jit_eval_step()(state2, batch)["logits"])
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
        print("CONVERTED_CKPT_OK")
    """)
    assert "CONVERTED_CKPT_OK" in out


def test_from_logits_bce_maps_to_logit_loss():
    """BinaryCrossentropy(from_logits=True) + linear head converts to the
    logits objective and trains (the probability path is covered elsewhere)."""
    out = _run("""
        import numpy as np, keras
        import openembedding_tpu as embed
        from openembedding_tpu.keras_compat import from_keras_model
        from openembedding_tpu.model import Trainer, binary_logloss

        # the 0.6 convergence bound is tight enough that unseeded keras
        # initializers flake it (~1 in 3); pin an init that converges
        # with margin (ratio 0.45 at 15 steps)
        keras.utils.set_random_seed(1)
        cat = keras.Input(shape=(2,), dtype="int32", name="cat")
        emb = keras.layers.Embedding(64, 4, name="emb")(cat)
        x = keras.layers.Flatten()(emb)
        out = keras.layers.Dense(1)(x)  # linear head: logits
        m = keras.Model(cat, out)
        m.compile(optimizer=keras.optimizers.Adagrad(learning_rate=0.5),
                  loss=keras.losses.BinaryCrossentropy(from_logits=True))

        emodel, opt = from_keras_model(m)
        assert emodel.loss_fn is binary_logloss
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, (64, 2)).astype(np.int32)
        y = (ids[:, 0] % 2).astype(np.float32)
        batch = {"sparse": {"cat": ids}, "dense": None, "label": y}
        tr = Trainer(emodel, opt)
        state = tr.init(batch)
        step = tr.jit_train_step()
        losses = []
        for _ in range(15):
            state, mtr = step(state, batch)
            losses.append(float(mtr["loss"]))
        assert losses[-1] < losses[0] * 0.6, losses
        print("FROM_LOGITS_OK")
    """)
    assert "FROM_LOGITS_OK" in out
