"""Optimizer parity tests.

Mirrors the reference's `test/optimizer_test.py` (each optimizer config run against the
real Keras apply path on identical gradients) plus tight parity against independent
numpy implementations of the reference formulas (`variable/EmbeddingOptimizer.h`), and
the sparse-specific semantics: duplicate grads summed, update once per unique id,
untouched rows bit-identical, per-row beta^t.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.ops.sparse import sparse_apply_dense_table

DIM = 8
ROWS = 6


def rand_block(seed, rows=ROWS, dim=DIM):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(rows, dim)).astype(np.float32)
    g = rng.normal(size=(rows, dim)).astype(np.float32)
    return w, g


# -- independent numpy references of the TF formulas ------------------------

def np_sgd(w, g, s, lr=0.01, momentum=0.0, nesterov=False):
    m = s["moment"] * momentum + lr * g
    w = w - (m * momentum + lr * g) if nesterov else w - m
    return w, {"moment": m}


def np_adagrad(w, g, s, lr=0.001, eps=1e-7):
    a = s["accum"] + g * g
    return w - lr * g / (np.sqrt(a) + eps), {"accum": a}


def np_adadelta(w, g, s, lr=0.001, rho=0.95, eps=1e-7):
    a = s["accum"] * rho + g * g * (1 - rho)
    upd = g * np.sqrt(s["accum_update"] + eps) / np.sqrt(a + eps)
    au = s["accum_update"] * rho + upd * upd * (1 - rho)
    return w - lr * upd, {"accum": a, "accum_update": au}


def np_adam(w, g, s, lr=0.001, b1=0.9, b2=0.999, eps=1e-7):
    b1t = s["beta_1_t"] * b1
    b2t = s["beta_2_t"] * b2
    lr_t = lr * np.sqrt(1 - b2t) / (1 - b1t)
    m = s["m"] * b1 + g * (1 - b1)
    v = s["v"] * b2 + g * g * (1 - b2)
    return w - lr_t * m / (np.sqrt(v) + eps), {
        "m": m, "v": v, "beta_1_t": b1t, "beta_2_t": b2t}


def np_adamax(w, g, s, lr=0.001, b1=0.9, b2=0.999, eps=1e-7):
    b1t = s["beta_1_t"] * b1
    lr_t = lr / (1 - b1t)
    m = s["m"] * b1 + g * (1 - b1)
    v = np.maximum(np.abs(g), s["v"] * b2)
    return w - lr_t * m / (v + eps), {"m": m, "v": v, "beta_1_t": b1t}


def np_ftrl(w, g, s, lr=0.001, l1=0.0, l2=0.0, l2s=0.0, lr_power=-0.5, beta=0.0):
    accum, linear = s["accum"], s["linear"]
    adj_l2 = l2 + beta / lr / 2
    gg = g + 2 * l2s * w
    accum_new = accum + g * g
    p = -lr_power
    sigma = (accum_new ** p - accum ** p) / lr
    linear = linear + gg - sigma * w
    quad = accum_new ** p / lr + 2 * adj_l2
    l1_adj = np.clip(linear, -l1, l1)
    return (l1_adj - linear) / quad, {"accum": accum_new, "linear": linear}


def np_rmsprop(w, g, s, lr=0.001, rho=0.9, momentum=0.0, eps=1e-7):
    a = s["accum"] * rho + g * g * (1 - rho)
    m = s["moment"] * momentum + lr * g / np.sqrt(a + eps)
    return w - m, {"accum": a, "moment": m}


CASES = [
    (embed.SGD(learning_rate=0.05), np_sgd, dict(lr=0.05)),
    (embed.SGD(learning_rate=0.05, momentum=0.9), np_sgd, dict(lr=0.05, momentum=0.9)),
    (embed.SGD(learning_rate=0.05, momentum=0.9, nesterov=True), np_sgd,
     dict(lr=0.05, momentum=0.9, nesterov=True)),
    (embed.Adagrad(learning_rate=0.1), np_adagrad, dict(lr=0.1)),
    (embed.Adadelta(learning_rate=0.7), np_adadelta, dict(lr=0.7)),
    (embed.Adam(learning_rate=0.01), np_adam, dict(lr=0.01)),
    (embed.Adamax(learning_rate=0.01), np_adamax, dict(lr=0.01)),
    (embed.Ftrl(learning_rate=0.05), np_ftrl, dict(lr=0.05)),
    (embed.Ftrl(learning_rate=0.05, l1_regularization_strength=0.01,
                l2_regularization_strength=0.02,
                l2_shrinkage_regularization_strength=0.01, beta=0.1), np_ftrl,
     dict(lr=0.05, l1=0.01, l2=0.02, l2s=0.01, beta=0.1)),
    (embed.Ftrl(learning_rate=0.05, learning_rate_power=-0.7), np_ftrl,
     dict(lr=0.05, lr_power=-0.7)),
    (embed.RMSprop(learning_rate=0.01), np_rmsprop, dict(lr=0.01)),
    (embed.RMSprop(learning_rate=0.01, momentum=0.9), np_rmsprop,
     dict(lr=0.01, momentum=0.9)),
]


@pytest.mark.parametrize("opt,np_fn,np_kwargs",
                         CASES, ids=lambda c: getattr(c, "category", None) or "")
def test_numpy_parity_multi_step(opt, np_fn, np_kwargs):
    w, _ = rand_block(0)
    slots = {k: np.asarray(v) for k, v in
             opt.init_slots(ROWS, DIM, jnp.float32).items()}
    jw = jnp.asarray(w)
    jslots = {k: jnp.asarray(v) for k, v in slots.items()}
    counts = jnp.ones((ROWS,), jnp.int32)
    apply_fn = jax.jit(opt.apply)
    for step in range(5):
        _, g = rand_block(step + 1)
        jw, jslots = apply_fn(jw, jslots, jnp.asarray(g), counts)
        w, slots = np_fn(w, g, slots, **np_kwargs)
    np.testing.assert_allclose(np.asarray(jw), w, rtol=2e-5, atol=2e-6)
    for k in slots:
        np.testing.assert_allclose(np.asarray(jslots[k]), slots[k],
                                   rtol=2e-5, atol=2e-6, err_msg=k)


@pytest.mark.parametrize("opt", [c[0] for c in CASES],
                         ids=[f"{c[0].category}{i}" for i, c in enumerate(CASES)])
def test_untouched_rows_bit_identical(opt):
    w, g = rand_block(3)
    slots = opt.init_slots(ROWS, DIM, jnp.float32)
    # touch only rows 1 and 4
    counts = jnp.asarray([0, 2, 0, 0, 1, 0], jnp.int32)
    new_w, new_slots = opt.apply(jnp.asarray(w), slots, jnp.asarray(g), counts)
    untouched = np.asarray([0, 2, 3, 5])
    np.testing.assert_array_equal(np.asarray(new_w)[untouched], w[untouched])
    for k in slots:
        np.testing.assert_array_equal(np.asarray(new_slots[k])[untouched],
                                      np.asarray(slots[k])[untouched], err_msg=k)
    touched = np.asarray([1, 4])
    assert not np.allclose(np.asarray(new_w)[touched], w[touched])


def test_sparse_apply_sums_duplicates_once():
    """Duplicate-id grads must be summed and the optimizer applied ONCE per unique id
    (reference: `MpscGradientReducer.h:26-53`, `EmbeddingOptimizerVariable.h:283-296`).
    Adagrad distinguishes sum-then-apply from apply-per-duplicate."""
    opt = embed.Adagrad(learning_rate=0.1)
    vocab, dim = 10, 4
    rng = np.random.default_rng(0)
    weights = jnp.asarray(rng.normal(size=(vocab, dim)).astype(np.float32))
    slots = opt.init_slots(vocab, dim, jnp.float32)
    ids = jnp.asarray([3, 7, 3, 3, 7, 1], jnp.int32)
    grads = jnp.asarray(rng.normal(size=(6, dim)).astype(np.float32))
    new_w, new_slots = sparse_apply_dense_table(opt, weights, slots, ids, grads)

    w = np.asarray(weights).copy()
    accum = np.full((vocab, dim), 0.1, np.float32)
    for uid in [1, 3, 7]:
        g = np.asarray(grads)[np.asarray(ids) == uid].sum(axis=0)
        w[uid], s = np_adagrad(w[uid], g, {"accum": accum[uid]}, lr=0.1)
        accum[uid] = s["accum"]
    np.testing.assert_allclose(np.asarray(new_w), w, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_slots["accum"]), accum,
                               rtol=1e-5, atol=1e-6)


def test_test_optimizer_count_semantics():
    """The `test` optimizer divides by count and flips state — the contract the
    self-checking cluster tests rely on (`EmbeddingOptimizer.h:366-390`)."""
    opt = embed.TestOptimizer(learning_rate=0.1, flip=100.0, init=0.0)
    w = jnp.zeros((2, 3), jnp.float32)
    slots = opt.init_slots(2, 3, jnp.float32)
    g = jnp.ones((2, 3), jnp.float32) * 6.0
    counts = jnp.asarray([2, 3], jnp.int32)
    new_w, new_slots = opt.apply(w, slots, g, counts)
    # state flips 0 -> 100; w += 0.1*6/count + 100
    np.testing.assert_allclose(np.asarray(new_w)[0], 100.3, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_w)[1], 100.2, rtol=1e-6)
    new_w2, new_slots2 = opt.apply(new_w, new_slots, g, counts)
    # state flips back to 0
    np.testing.assert_allclose(np.asarray(new_slots2["flip_state"]), 0.0, atol=1e-6)


def test_keras_cross_check():
    """Loose cross-check vs real Keras (the reference asserts summed abs error < 10 vs
    TF, `test/optimizer_test.py:54-72`; Keras 3 moved epsilon placement slightly so the
    tolerance is loose-but-meaningful)."""
    keras = pytest.importorskip("keras")
    import tensorflow as tf

    configs = [
        (embed.SGD(learning_rate=0.05), keras.optimizers.SGD(learning_rate=0.05)),
        (embed.SGD(learning_rate=0.05, momentum=0.9),
         keras.optimizers.SGD(learning_rate=0.05, momentum=0.9)),
        (embed.Adagrad(learning_rate=0.1, initial_accumulator_value=0.1),
         keras.optimizers.Adagrad(learning_rate=0.1, initial_accumulator_value=0.1)),
        (embed.Adam(learning_rate=0.01), keras.optimizers.Adam(learning_rate=0.01)),
        (embed.RMSprop(learning_rate=0.01), keras.optimizers.RMSprop(learning_rate=0.01)),
        (embed.Ftrl(learning_rate=0.05, initial_accumulator_value=0.1),
         keras.optimizers.Ftrl(learning_rate=0.05, initial_accumulator_value=0.1)),
    ]
    for ours, theirs in configs:
        w0, _ = rand_block(11)
        var = tf.Variable(w0)
        jw = jnp.asarray(w0)
        jslots = ours.init_slots(ROWS, DIM, jnp.float32)
        counts = jnp.ones((ROWS,), jnp.int32)
        for step in range(5):
            _, g = rand_block(100 + step)
            theirs.apply_gradients([(tf.constant(g), var)])
            jw, jslots = ours.apply(jw, jslots, jnp.asarray(g), counts)
        err = np.abs(np.asarray(jw) - var.numpy()).sum()
        assert err < 0.5, f"{ours.category}: summed abs err {err}"


def test_make_optimizer_roundtrip():
    for opt in [c[0] for c in CASES] + [embed.TestOptimizer()]:
        again = embed.make_optimizer(opt.to_config())
        assert again == opt


def test_from_keras_rejections():
    keras = pytest.importorskip("keras")
    with pytest.raises(ValueError):
        embed.optimizers.from_keras(keras.optimizers.Adam(amsgrad=True))
    with pytest.raises(ValueError):
        embed.optimizers.from_keras(keras.optimizers.RMSprop(centered=True))
