"""SASRec sequential model + context-parallel SeqMeshTrainer integration.

The forward-parity test transplants the CP-trained table (gathered to id-major
order) and the replicated dense params into a single-device full-attention
trainer and checks logits match — proving the 2-D (data, seq) mesh, the tuple-
axis sparse exchange, and ring attention compose correctly end to end."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import openembedding_tpu as embed
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_sasrec, synthetic_sequences
from openembedding_tpu.parallel import SeqMeshTrainer, deinterleave_rows
from openembedding_tpu.parallel.trainer import MeshTrainer

VOCAB = 512
DIM = 16
SEQ = 32


def _mesh_2d(data, seq):
    devs = np.array(jax.devices()[:data * seq]).reshape(data, seq)
    return Mesh(devs, ("data", "seq"))


def _batches(n, batch=8, seed=0):
    return list(synthetic_sequences(batch, SEQ, VOCAB, seed=seed, steps=n))


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_cp_forward_matches_single_device(attention):
    mesh = _mesh_2d(2, 4)
    heads = 4  # ulysses re-shards heads over the seq axis: needs H % 4 == 0
    model_cp = make_sasrec(VOCAB, DIM, attention=attention, num_heads=heads,
                           compute_dtype=jnp.float32)
    tr_cp = SeqMeshTrainer(model_cp, embed.Adagrad(learning_rate=0.1),
                           mesh=mesh, seed=7)
    batch = _batches(1)[0]
    state_cp = tr_cp.init(batch)
    out_cp = tr_cp.jit_eval_step(batch, state_cp)(state_cp, batch)
    logits_cp = np.asarray(out_cp["logits"])

    # transplant: gathered id-major table + replicated dense params -> 1 device
    model_1 = make_sasrec(VOCAB, DIM, attention="full", num_heads=heads,
                          compute_dtype=jnp.float32)
    tr_1 = Trainer(model_1, embed.Adagrad(learning_rate=0.1), seed=7)
    state_1 = tr_1.init(batch)
    table_cp = state_cp.tables["item"]
    id_major = deinterleave_rows(np.asarray(table_cp.weights), 8, VOCAB)
    state_1 = state_1.replace(
        dense_params=jax.device_get(state_cp.dense_params),
        tables={"item": state_1.tables["item"].replace(
            weights=jnp.asarray(id_major))})
    logits_1 = np.asarray(tr_1.jit_eval_step()(state_1, batch)["logits"])
    np.testing.assert_allclose(logits_cp, logits_1, rtol=2e-4, atol=2e-4)


def test_cp_training_loss_drops():
    mesh = _mesh_2d(2, 4)
    model = make_sasrec(VOCAB, DIM, attention="ring")
    tr = SeqMeshTrainer(model, embed.Adagrad(learning_rate=0.3), mesh=mesh)
    batch = _batches(1, batch=16)[0]
    state = tr.init(batch)
    step = tr.jit_train_step(batch, state)
    state, m0 = step(state, batch)
    loss0 = float(m0["loss"])
    for _ in range(40):
        state, m = step(state, batch)
    loss1 = float(m["loss"])
    assert np.isfinite(loss1) and loss1 < loss0 * 0.8, (loss0, loss1)


def test_single_device_sasrec_trains():
    model = make_sasrec(VOCAB, DIM, attention="full")
    tr = Trainer(model, embed.Adagrad(learning_rate=0.3))
    batch = _batches(1, batch=16)[0]
    state = tr.init(batch)
    step = tr.jit_train_step()
    state, m0 = step(state, batch)
    for _ in range(40):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"]) * 0.8


def test_cp_loss_normalization_matches_single_device():
    """Padding-heavy seq shards must not be upweighted: the CP loss equals the
    single-device loss of the same batch and params (global mask count)."""
    mesh = _mesh_2d(2, 4)
    model_cp = make_sasrec(VOCAB, DIM, attention="ring",
                           compute_dtype=jnp.float32)
    tr_cp = SeqMeshTrainer(model_cp, embed.Adagrad(learning_rate=0.1),
                           mesh=mesh, seed=7)
    batch = _batches(1)[0]  # lengths in [S/2, S]: last shard is padding-heavy
    assert (np.asarray(batch["label"]).sum(axis=1) < SEQ).any()
    state_cp = tr_cp.init(batch)
    loss_cp = float(tr_cp.jit_eval_step(batch, state_cp)(state_cp, batch)["loss"])

    model_1 = make_sasrec(VOCAB, DIM, attention="full",
                          compute_dtype=jnp.float32)
    tr_1 = Trainer(model_1, embed.Adagrad(learning_rate=0.1), seed=7)
    state_1 = tr_1.init(batch)
    id_major = deinterleave_rows(
        np.asarray(state_cp.tables["item"].weights), 8, VOCAB)
    state_1 = state_1.replace(
        dense_params=jax.device_get(state_cp.dense_params),
        tables={"item": state_1.tables["item"].replace(
            weights=jnp.asarray(id_major))})
    loss_1 = float(tr_1.jit_eval_step()(state_1, batch)["loss"])
    np.testing.assert_allclose(loss_cp, loss_1, rtol=1e-5)


def test_cp_export_serves_with_local_attention(tmp_path):
    """A ring-attention-trained model must export to a servable standalone
    model (serving runs outside shard_map -> attention normalized to full)."""
    from openembedding_tpu.export import StandaloneModel, export_standalone
    mesh = _mesh_2d(2, 4)
    model = make_sasrec(VOCAB, DIM, attention="ring")
    tr = SeqMeshTrainer(model, embed.Adagrad(learning_rate=0.1), mesh=mesh)
    batch = _batches(1)[0]
    state = tr.init(batch)
    path = str(tmp_path / "sasrec_export")
    export_standalone(state, model, path, num_shards=tr.num_shards)
    sm = StandaloneModel.load(path)
    assert sm.model.module.attention == "full"
    logits = np.asarray(sm.predict(batch))
    assert logits.shape == np.asarray(batch["label"]).shape + (2,)
    assert np.isfinite(logits).all()


def test_sasrec_rejects_overlong_sequences():
    model = make_sasrec(VOCAB, DIM, attention="full", max_len=16)
    tr = Trainer(model, embed.Adagrad())
    batch = _batches(1, batch=2)[0]  # SEQ=32 > max_len=16
    with pytest.raises(ValueError, match="exceeds"):
        tr.init(batch)


def test_sasrec_padding_rows_do_not_train():
    """Ids appearing ONLY at masked (label 0) positions are -1 in the synthetic
    stream; craft a batch where a real id sits at a masked position and check
    its row never trains (pull returns rows but loss-masking zeroes its grad —
    id -1 padding additionally pulls zeros)."""
    model = make_sasrec(VOCAB, DIM, attention="full", compute_dtype=jnp.float32)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.1))
    base = _batches(1, batch=2)[0]
    ids = np.asarray(base["sparse"]["item"]).copy()
    label = np.asarray(base["label"]).copy()
    label[:, -1] = 0.0          # mask the final position everywhere
    used = set(np.unique(ids).tolist())
    probe = next(i for i in range(VOCAB - 1, -1, -1) if i not in used)
    ids[:, :, -1] = probe        # place it only at the masked position
    batch = {"sparse": {"item": ids}, "label": label}
    state = tr.init(batch)
    before = np.asarray(state.tables["item"].weights)[probe].copy()
    state, _ = tr.jit_train_step()(state, batch)
    after = np.asarray(state.tables["item"].weights)[probe]
    np.testing.assert_array_equal(before, after)


# ---------------------------------------------------------------- BERT4Rec

def _masked_batches(n, batch=8, seed=0):
    from openembedding_tpu.models import synthetic_masked_sequences
    return list(synthetic_masked_sequences(batch, SEQ, VOCAB, seed=seed,
                                           steps=n))


def test_bert4rec_single_device_trains():
    """Masked-item (Cloze) training learns the planted Markov chains: loss
    drops AND the model ranks the true masked item above the sampled
    negative far better than chance."""
    from openembedding_tpu.models import make_bert4rec

    model = make_bert4rec(VOCAB, DIM, attention="full")
    tr = Trainer(model, embed.Adagrad(learning_rate=0.3))
    batch = _masked_batches(1, batch=16)[0]
    state = tr.init(batch)
    step = tr.jit_train_step()
    state, m0 = step(state, batch)
    for _ in range(60):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"]) * 0.6
    out = tr.jit_eval_step()(state, batch)
    logits = np.asarray(out["logits"])        # (B, S, 2) = [pos, neg]
    mask = np.asarray(batch["label"]) > 0
    acc = float((logits[..., 0] > logits[..., 1])[mask].mean())
    assert acc > 0.85, acc


def test_bert4rec_mask_token_is_a_real_row():
    """The [MASK] id (== vocabulary) must resolve to a trainable table row,
    not alias item 0 or fall out of range."""
    from openembedding_tpu.models import bert4rec_mask_id, make_bert4rec

    model = make_bert4rec(VOCAB, DIM, attention="full")
    assert model.specs["item"].input_dim == VOCAB + 1
    mask_id = bert4rec_mask_id(VOCAB)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.3))
    batch = _masked_batches(1, batch=8)[0]
    assert (np.asarray(batch["sparse"]["item"])[:, 0] == mask_id).any()
    state = tr.init(batch)
    before = np.asarray(state.tables["item"].weights)[mask_id].copy()
    state, _ = tr.jit_train_step()(state, batch)
    after = np.asarray(state.tables["item"].weights)[mask_id]
    assert not np.allclose(before, after)  # the mask row itself trains


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_bert4rec_cp_forward_matches_single_device(attention):
    """BIDIRECTIONAL context-parallel attention (causal=False through the
    ring/Ulysses paths) matches the single-device oracle — the non-causal
    twin of test_cp_forward_matches_single_device."""
    from openembedding_tpu.models import make_bert4rec

    mesh = _mesh_2d(2, 4)
    heads = 4
    model_cp = make_bert4rec(VOCAB, DIM, attention=attention,
                             num_heads=heads, compute_dtype=jnp.float32)
    tr_cp = SeqMeshTrainer(model_cp, embed.Adagrad(learning_rate=0.1),
                           mesh=mesh, seed=7)
    batch = _masked_batches(1)[0]
    state_cp = tr_cp.init(batch)
    out_cp = tr_cp.jit_eval_step(batch, state_cp)(state_cp, batch)
    logits_cp = np.asarray(out_cp["logits"])

    model_1 = make_bert4rec(VOCAB, DIM, attention="full", num_heads=heads,
                            compute_dtype=jnp.float32)
    tr_1 = Trainer(model_1, embed.Adagrad(learning_rate=0.1), seed=7)
    state_1 = tr_1.init(batch)
    table_cp = state_cp.tables["item"]
    id_major = deinterleave_rows(np.asarray(table_cp.weights), 8, VOCAB + 1)
    state_1 = state_1.replace(
        dense_params=jax.device_get(state_cp.dense_params),
        tables={"item": state_1.tables["item"].replace(
            weights=jnp.asarray(id_major))})
    logits_1 = np.asarray(tr_1.jit_eval_step()(state_1, batch)["logits"])
    np.testing.assert_allclose(logits_cp, logits_1, rtol=2e-4, atol=2e-4)


def test_bert4rec_config_round_trip(tmp_path):
    """Zoo recipe rebuild + standalone export serve with full attention."""
    from openembedding_tpu.export import StandaloneModel, export_standalone
    from openembedding_tpu.models import from_config, make_bert4rec

    model = make_bert4rec(VOCAB, DIM, attention="ring")
    again = from_config(model.config)
    assert again.module.causal is False
    assert again.specs["item"].input_dim == VOCAB + 1

    tr = SeqMeshTrainer(model, embed.Adagrad(learning_rate=0.1),
                        mesh=_mesh_2d(2, 4))
    batch = _masked_batches(1)[0]
    state = tr.init(batch)
    path = str(tmp_path / "bert4rec_export")
    export_standalone(state, model, path, num_shards=tr.num_shards)
    sm = StandaloneModel.load(path)
    assert sm.model.module.attention == "full"
    assert sm.model.module.causal is False
    logits = np.asarray(sm.predict(batch))
    assert logits.shape == np.asarray(batch["label"]).shape + (2,)
    assert np.isfinite(logits).all()


def test_bert4rec_logits_invariant_to_pad_width():
    """THE bidirectional-padding pin: the same histories padded to S and to
    S+8 must score identically at the real positions. Without the key-padding
    mask (kv_valid through reference/ring/ulysses attention), pad slots soak
    up softmax mass and the logits shift with the pad width."""
    from openembedding_tpu.models import make_bert4rec

    model = make_bert4rec(VOCAB, DIM, attention="full",
                          compute_dtype=jnp.float32)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.3))
    batch = _masked_batches(1, batch=8)[0]
    ids = np.asarray(batch["sparse"]["item"])          # (B, 3, S)
    label = np.asarray(batch["label"])
    state = tr.init(batch)
    # train a little so the answer isn't about init symmetry
    step = tr.jit_train_step()
    for _ in range(5):
        state, _ = step(state, batch)

    wide_ids = np.concatenate(
        [ids, np.full(ids.shape[:2] + (8,), -1, ids.dtype)], axis=-1)
    wide = {"sparse": {"item": wide_ids},
            "label": np.concatenate(
                [label, np.zeros((label.shape[0], 8), label.dtype)], axis=-1)}
    ev = tr.jit_eval_step()
    narrow_logits = np.asarray(ev(state, batch)["logits"])
    wide_logits = np.asarray(ev(state, wide)["logits"])
    np.testing.assert_allclose(wide_logits[:, :ids.shape[-1]], narrow_logits,
                               rtol=1e-5, atol=1e-6)


def test_key_padding_mask_derives_from_ids_not_zero_rows():
    """ADVICE r5: the key-padding mask must come from the id array, not from
    the exact-zero-row property of pulled embeddings. With a Constant(0)
    item table EVERY real row is all-zero at step 0 — the old heuristic
    masked every key (all-(-inf) attention logits), the id-derived mask
    keeps real positions valid and the forward pass finite."""
    from openembedding_tpu.models.sequential import SASRec, ITEM

    model = make_sasrec(VOCAB, DIM, attention="full")
    model.specs[ITEM] = dataclasses.replace(
        model.specs[ITEM], initializer=embed.Constant(0.0))
    tr = Trainer(model, embed.Adagrad(learning_rate=0.1))
    batch = _batches(1)[0]
    state = tr.init(batch)
    out = tr.jit_eval_step()(state, batch)
    assert np.isfinite(np.asarray(out["logits"])).all()
    assert np.isfinite(float(out["loss"]))

    # the mask itself: raw ids win over row content (a zero row at a REAL
    # position stays a valid attention key; pads (-1) never do)
    ids = np.asarray(batch["sparse"][ITEM])             # (B, 3, S)
    hist_zero_rows = jnp.zeros((ids.shape[0], ids.shape[-1], DIM))
    mod = SASRec(dim=DIM)
    got = mod._kv_valid({"__ids__": {ITEM: jnp.asarray(ids)}},
                        hist_zero_rows)
    np.testing.assert_array_equal(np.asarray(got), ids[:, 0] >= 0)
    # fallback (no ids attached): the legacy zero-row heuristic
    got_fb = mod._kv_valid({}, hist_zero_rows)
    assert not np.asarray(got_fb).any()
