"""SASRec sequential model + context-parallel SeqMeshTrainer integration.

The forward-parity test transplants the CP-trained table (gathered to id-major
order) and the replicated dense params into a single-device full-attention
trainer and checks logits match — proving the 2-D (data, seq) mesh, the tuple-
axis sparse exchange, and ring attention compose correctly end to end."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import openembedding_tpu as embed
from openembedding_tpu.model import Trainer
from openembedding_tpu.models import make_sasrec, synthetic_sequences
from openembedding_tpu.parallel import SeqMeshTrainer, deinterleave_rows
from openembedding_tpu.parallel.trainer import MeshTrainer

VOCAB = 512
DIM = 16
SEQ = 32


def _mesh_2d(data, seq):
    devs = np.array(jax.devices()[:data * seq]).reshape(data, seq)
    return Mesh(devs, ("data", "seq"))


def _batches(n, batch=8, seed=0):
    return list(synthetic_sequences(batch, SEQ, VOCAB, seed=seed, steps=n))


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_cp_forward_matches_single_device(attention):
    mesh = _mesh_2d(2, 4)
    heads = 4  # ulysses re-shards heads over the seq axis: needs H % 4 == 0
    model_cp = make_sasrec(VOCAB, DIM, attention=attention, num_heads=heads,
                           compute_dtype=jnp.float32)
    tr_cp = SeqMeshTrainer(model_cp, embed.Adagrad(learning_rate=0.1),
                           mesh=mesh, seed=7)
    batch = _batches(1)[0]
    state_cp = tr_cp.init(batch)
    out_cp = tr_cp.jit_eval_step(batch, state_cp)(state_cp, batch)
    logits_cp = np.asarray(out_cp["logits"])

    # transplant: gathered id-major table + replicated dense params -> 1 device
    model_1 = make_sasrec(VOCAB, DIM, attention="full", num_heads=heads,
                          compute_dtype=jnp.float32)
    tr_1 = Trainer(model_1, embed.Adagrad(learning_rate=0.1), seed=7)
    state_1 = tr_1.init(batch)
    table_cp = state_cp.tables["item"]
    id_major = deinterleave_rows(np.asarray(table_cp.weights), 8, VOCAB)
    state_1 = state_1.replace(
        dense_params=jax.device_get(state_cp.dense_params),
        tables={"item": state_1.tables["item"].replace(
            weights=jnp.asarray(id_major))})
    logits_1 = np.asarray(tr_1.jit_eval_step()(state_1, batch)["logits"])
    np.testing.assert_allclose(logits_cp, logits_1, rtol=2e-4, atol=2e-4)


def test_cp_training_loss_drops():
    mesh = _mesh_2d(2, 4)
    model = make_sasrec(VOCAB, DIM, attention="ring")
    tr = SeqMeshTrainer(model, embed.Adagrad(learning_rate=0.3), mesh=mesh)
    batch = _batches(1, batch=16)[0]
    state = tr.init(batch)
    step = tr.jit_train_step(batch, state)
    state, m0 = step(state, batch)
    loss0 = float(m0["loss"])
    for _ in range(40):
        state, m = step(state, batch)
    loss1 = float(m["loss"])
    assert np.isfinite(loss1) and loss1 < loss0 * 0.8, (loss0, loss1)


def test_single_device_sasrec_trains():
    model = make_sasrec(VOCAB, DIM, attention="full")
    tr = Trainer(model, embed.Adagrad(learning_rate=0.3))
    batch = _batches(1, batch=16)[0]
    state = tr.init(batch)
    step = tr.jit_train_step()
    state, m0 = step(state, batch)
    for _ in range(40):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"]) * 0.8


def test_cp_loss_normalization_matches_single_device():
    """Padding-heavy seq shards must not be upweighted: the CP loss equals the
    single-device loss of the same batch and params (global mask count)."""
    mesh = _mesh_2d(2, 4)
    model_cp = make_sasrec(VOCAB, DIM, attention="ring",
                           compute_dtype=jnp.float32)
    tr_cp = SeqMeshTrainer(model_cp, embed.Adagrad(learning_rate=0.1),
                           mesh=mesh, seed=7)
    batch = _batches(1)[0]  # lengths in [S/2, S]: last shard is padding-heavy
    assert (np.asarray(batch["label"]).sum(axis=1) < SEQ).any()
    state_cp = tr_cp.init(batch)
    loss_cp = float(tr_cp.jit_eval_step(batch, state_cp)(state_cp, batch)["loss"])

    model_1 = make_sasrec(VOCAB, DIM, attention="full",
                          compute_dtype=jnp.float32)
    tr_1 = Trainer(model_1, embed.Adagrad(learning_rate=0.1), seed=7)
    state_1 = tr_1.init(batch)
    id_major = deinterleave_rows(
        np.asarray(state_cp.tables["item"].weights), 8, VOCAB)
    state_1 = state_1.replace(
        dense_params=jax.device_get(state_cp.dense_params),
        tables={"item": state_1.tables["item"].replace(
            weights=jnp.asarray(id_major))})
    loss_1 = float(tr_1.jit_eval_step()(state_1, batch)["loss"])
    np.testing.assert_allclose(loss_cp, loss_1, rtol=1e-5)


def test_cp_export_serves_with_local_attention(tmp_path):
    """A ring-attention-trained model must export to a servable standalone
    model (serving runs outside shard_map -> attention normalized to full)."""
    from openembedding_tpu.export import StandaloneModel, export_standalone
    mesh = _mesh_2d(2, 4)
    model = make_sasrec(VOCAB, DIM, attention="ring")
    tr = SeqMeshTrainer(model, embed.Adagrad(learning_rate=0.1), mesh=mesh)
    batch = _batches(1)[0]
    state = tr.init(batch)
    path = str(tmp_path / "sasrec_export")
    export_standalone(state, model, path, num_shards=tr.num_shards)
    sm = StandaloneModel.load(path)
    assert sm.model.module.attention == "full"
    logits = np.asarray(sm.predict(batch))
    assert logits.shape == np.asarray(batch["label"]).shape + (2,)
    assert np.isfinite(logits).all()


def test_sasrec_rejects_overlong_sequences():
    model = make_sasrec(VOCAB, DIM, attention="full", max_len=16)
    tr = Trainer(model, embed.Adagrad())
    batch = _batches(1, batch=2)[0]  # SEQ=32 > max_len=16
    with pytest.raises(ValueError, match="exceeds"):
        tr.init(batch)


def test_sasrec_padding_rows_do_not_train():
    """Ids appearing ONLY at masked (label 0) positions are -1 in the synthetic
    stream; craft a batch where a real id sits at a masked position and check
    its row never trains (pull returns rows but loss-masking zeroes its grad —
    id -1 padding additionally pulls zeros)."""
    model = make_sasrec(VOCAB, DIM, attention="full", compute_dtype=jnp.float32)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.1))
    base = _batches(1, batch=2)[0]
    ids = np.asarray(base["sparse"]["item"]).copy()
    label = np.asarray(base["label"]).copy()
    label[:, -1] = 0.0          # mask the final position everywhere
    used = set(np.unique(ids).tolist())
    probe = next(i for i in range(VOCAB - 1, -1, -1) if i not in used)
    ids[:, :, -1] = probe        # place it only at the masked position
    batch = {"sparse": {"item": ids}, "label": label}
    state = tr.init(batch)
    before = np.asarray(state.tables["item"].weights)[probe].copy()
    state, _ = tr.jit_train_step()(state, batch)
    after = np.asarray(state.tables["item"].weights)[probe]
    np.testing.assert_array_equal(before, after)
